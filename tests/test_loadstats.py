"""Fleet pressure plane + training goodput (ISSUE 15).

Tiers, cheapest first:

* host-only units — RollingQuantile windows, LoadSnapshot/FleetSnapshot
  shapes, SloTargets validation, SloMonitor burn-rate escalation;
* fleet snapshot aggregation under replica loss (DOWN reported, never
  dropped), mid-rollout (RELOADING reported), and post-recreate;
* the identity gates — serving token streams IDENTICAL with the monitor
  observing every step vs not, across contiguous / paged / overlapped /
  sharded engines (observation is passive host reads by construction;
  these tests are the proof);
* the chaos drill — injected slow-step faults drive one replica of a
  supervised fleet HEALTHY -> PRESSURED -> SATURATED, with the pressure
  record on the ledger row and the flight-recorder dump on disk;
* goodput — bucket-sum == wall-time property, the FLOPs estimator (dense
  + MoE, hand-computed), and training-loss bit-parity goodput-on vs off.
"""

import asyncio
import dataclasses
import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
from tpu_nexus.core.telemetry import METRIC_NAMES, RecordingMetrics
from tpu_nexus.k8s.fake import FakeKubeClient
from tpu_nexus.models import LlamaConfig, MoeConfig
from tpu_nexus.models.generate import generate
from tpu_nexus.models.llama import llama_init
from tpu_nexus.serving import (
    PRESSURE_ACTIONS,
    PRESSURE_DOWN,
    PRESSURE_HEALTHY,
    PRESSURE_PRESSURED,
    PRESSURE_SATURATED,
    PRESSURE_SEVERITY,
    PRESSURE_STATES,
    FleetSnapshot,
    FleetSupervisor,
    LoadSnapshot,
    ModelExecutor,
    PagedModelExecutor,
    RequestState,
    RollingQuantile,
    ServingEngine,
    ServingFleet,
    ServingMetrics,
    SloMonitor,
    SloTargets,
    emit_fleet_snapshot,
    emit_load_snapshot,
    worst_pressure,
)
from tpu_nexus.serving.loadstats import numeric_fields
from tpu_nexus.workload.faults import FaultyExecutor
from tpu_nexus.workload.goodput import (
    BUCKET_DATA,
    BUCKET_INIT,
    BUCKET_OTHER,
    BUCKET_STEP,
    BUCKETS,
    GoodputMeter,
    NullGoodputMeter,
    chip_peak_flops,
    model_flops_per_token,
)

NS = "nexus"
FLEET_JS = "svc"
ALGO = "svc-algo"


class FakeExecutor:
    """Deterministic device stand-in (the test_serving_engine shape)."""

    def __init__(self, num_slots=2, max_len=64):
        self.num_slots = num_slots
        self.max_len = max_len

    def begin(self, slot, prompt):
        return (int(prompt[-1]) + 1) % 1000

    def step(self, tokens, cursors):
        return np.asarray(tokens) + 1

    def swap_params(self, params):
        self.params = params


def fake_engine(slots=2, max_len=64, clock=None, executor=None):
    kwargs = {} if clock is None else {"clock": clock}
    return ServingEngine(executor or FakeExecutor(slots, max_len), **kwargs)


def targets(**over):
    base = dict(ttft_p99_s=0.01, short_window=2, long_window=4)
    base.update(over)
    return SloTargets(**base)


def snap(replica="r0", **over):
    return LoadSnapshot(replica=replica, **over)


def fleet_of(*snaps):
    return FleetSnapshot.aggregate({s.replica: s for s in snaps})


# -- RollingQuantile -----------------------------------------------------------


class TestRollingQuantile:
    def test_bounded_window_and_total(self):
        rq = RollingQuantile(window=4)
        for i in range(10):
            rq.append(float(i))
        assert len(rq) == 4
        assert list(rq) == [6.0, 7.0, 8.0, 9.0]
        assert rq.total == 10

    def test_quantiles_whole_and_recent(self):
        rq = RollingQuantile(window=100)
        for i in range(100):
            rq.append(float(i))
        assert rq.quantile(50) == 50.0
        assert rq.quantile(100) == 99.0
        assert rq.quantile(99) == 98.0  # nearest rank: round(.99 * 99)
        # recent window sees only the tail
        assert rq.quantile(100, recent=10) == 99.0
        assert rq.quantile(0, recent=10) == 90.0

    def test_list_compat_surface(self):
        rq = RollingQuantile(window=8)
        assert rq == []
        assert not rq
        rq.append(0.5)
        assert rq == [0.5] and rq[0] == 0.5 and bool(rq)
        assert rq == pytest.approx([0.5])

    def test_degenerate(self):
        rq = RollingQuantile(window=8)
        assert rq.quantile(99) == 0.0
        assert rq.quantile(50, recent=0) == 0.0
        with pytest.raises(ValueError, match="window"):
            RollingQuantile(window=0)

    def test_serving_metrics_series_are_bounded(self):
        m = ServingMetrics()
        for name in ("ttft_s", "tpot_s", "queue_wait_s", "dispatch_s"):
            series = getattr(m, name)
            assert isinstance(series, RollingQuantile), name
        assert m.ttft_s.window == ServingMetrics.WINDOW
        assert m.dispatch_s.window == 4096

    def test_slo_window_reads_recent_samples(self):
        m = ServingMetrics()
        # old regime: slow; recent SNAPSHOT_WINDOW samples: fast
        for _ in range(ServingMetrics.WINDOW - ServingMetrics.SNAPSHOT_WINDOW):
            m.tpot_s.append(1.0)
        for _ in range(ServingMetrics.SNAPSHOT_WINDOW):
            m.tpot_s.append(0.001)
        view = m.slo_window()
        assert view["tpot_p99_s"] == 0.001  # the boot-time tail is invisible
        # summary() still reports the whole retained window
        assert m.summary()["tpot_p99_s"] == 1.0

    def test_quantiles_match_single_quantile(self):
        # the one-sort multi-rank path must agree with quantile() rank
        # by rank, whole window and recent tail alike
        rq = RollingQuantile(window=64)
        for i in (5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0, 4.0, 6.0):
            rq.append(i)
        for recent in (None, 4):
            pair = rq.quantiles((50, 99), recent=recent)
            assert pair == [
                rq.quantile(50, recent=recent),
                rq.quantile(99, recent=recent),
            ]

    def test_slo_window_memo_invalidates_on_new_samples(self):
        # the memo keys on the series totals: identical until a sample
        # lands, fresh immediately after — and the returned dict is a
        # copy (a caller mutating it cannot poison later reads)
        m = ServingMetrics()
        m.tpot_s.append(0.5)
        first = m.slo_window()
        first["tpot_p99_s"] = -1.0
        assert m.slo_window()["tpot_p99_s"] == 0.5
        m.tpot_s.append(2.0)
        assert m.slo_window()["tpot_p99_s"] == 2.0
        # window-rotation edge: a full deque keeps len constant while
        # total keeps counting, so the memo still invalidates
        rq_metrics = ServingMetrics()
        rq_metrics.ttft_s = RollingQuantile(window=2)
        rq_metrics.ttft_s.append(1.0)
        rq_metrics.ttft_s.append(1.0)
        assert rq_metrics.slo_window()["ttft_p99_s"] == 1.0
        rq_metrics.ttft_s.append(3.0)
        assert rq_metrics.slo_window()["ttft_p99_s"] == 3.0


# -- LoadSnapshot / engine.load_snapshot ---------------------------------------


class TestLoadSnapshot:
    def test_engine_snapshot_plain_host_values(self):
        eng = fake_engine()
        for i in range(4):
            eng.submit(np.array([1, 2, 3]), 4, request_id=f"r{i}")
        eng.step()  # 2 admitted, 2 queued
        s = eng.load_snapshot()
        assert s.queue_depth == 2
        assert s.live_requests == 2
        assert s.slots_used == 2 and s.slots_free == 0
        assert s.engine_steps == 1
        for name in numeric_fields(LoadSnapshot):
            assert isinstance(getattr(s, name), (int, float)), name
        while eng.has_work:
            eng.step()
        s = eng.load_snapshot()
        assert s.queue_depth == 0 and s.live_requests == 0
        assert s.requests_retired == 4
        assert s.tokens_out == 16
        assert s.ttft_p99_s > 0 and s.tpot_p99_s > 0

    def test_paged_snapshot_reports_blocks(self):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        ex = PagedModelExecutor(params, cfg, num_slots=2, max_len=24, page_size=4)
        eng = ServingEngine(ex)
        eng.submit(np.arange(1, 7, dtype=np.int32), 4)
        eng.step()
        s = eng.load_snapshot()
        assert s.blocks_used > 0
        assert s.blocks_free > 0
        eng.run_until_drained()

    def test_down_placeholder_carries_cause(self):
        s = LoadSnapshot.down("r1", cause="replica-lost:x")
        assert s.state == PRESSURE_DOWN and s.down_cause == "replica-lost:x"
        assert s.queue_depth == 0

    def test_to_dict_round_trips_ints(self):
        s = snap(queue_depth=3, ttft_p99_s=0.5)
        d = s.to_dict()
        assert d["queue_depth"] == 3 and d["ttft_p99_s"] == 0.5
        json.dumps(d)  # ledger-details serializable

    def test_registry_parity_runtime_twin(self):
        # the NX016 static rule's runtime twin: every numeric field has
        # its registry row, and every prefixed row has its field
        load_fields = set(numeric_fields(LoadSnapshot))
        fleet_fields = set(numeric_fields(FleetSnapshot))
        for f in load_fields:
            assert f"load.{f}" in METRIC_NAMES, f
        for f in fleet_fields:
            assert f"fleet.load.{f}" in METRIC_NAMES, f
        for row in METRIC_NAMES:
            if row.startswith("fleet.load."):
                assert row[len("fleet.load."):] in fleet_fields, row
            elif row.startswith("load."):
                assert row[len("load."):] in load_fields, row

    def test_emit_covers_every_numeric_field(self):
        rec = RecordingMetrics()
        emit_load_snapshot(rec, snap(queue_depth=1), replica="rX")
        for f in numeric_fields(LoadSnapshot):
            assert f"load.{f}" in rec.gauges, f
        rec2 = RecordingMetrics()
        emit_fleet_snapshot(rec2, fleet_of(snap(), LoadSnapshot.down("r1")))
        for f in numeric_fields(FleetSnapshot):
            assert f"fleet.load.{f}" in rec2.gauges, f
        # down replicas emit no per-replica zeros (they'd read as idle)
        assert rec2.gauges["fleet.load.replicas_down"] == 1


# -- fleet snapshot aggregation ------------------------------------------------


class TestFleetSnapshot:
    def test_aggregates_live_replicas(self):
        fleet = ServingFleet()
        e0, e1 = fake_engine(), fake_engine()
        fleet.add_replica("r0", e0)
        fleet.add_replica("r1", e1)
        for i in range(6):
            fleet.submit(np.array([1, 2, 3]), 8, request_id=f"q{i}")
        fs = fleet.snapshot()
        assert fs.replicas_total == 2 and fs.replicas_serving == 2
        assert fs.live_requests + fs.queue_depth == 6
        assert set(fs.replicas) == {"r0", "r1"}
        assert all(s.replica == n for n, s in fs.replicas.items())

    def test_replica_loss_reported_not_dropped(self):
        fleet = ServingFleet()
        fleet.add_replica("r0", fake_engine())
        fleet.add_replica("r1", fake_engine())
        fleet.submit(np.array([1, 2, 3]), 4)
        fleet.kill_replica("r0", "replica-lost:test")
        fs = fleet.snapshot()
        assert fs.replicas_total == 2
        assert fs.replicas_down == 1
        assert fs.replicas["r0"].state == PRESSURE_DOWN
        assert fs.replicas["r0"].down_cause == "replica-lost:test"
        # and the fold into summary() (the ISSUE's fix satellite)
        load = fleet.summary()["load"]
        assert load["replicas_down"] == 1
        assert load["replicas"]["r0"]["state"] == PRESSURE_DOWN

    def test_mid_rollout_reloading_reported(self):
        class Source:
            def restore_params(self, step):
                return "params@%d" % step

        fleet = ServingFleet()
        fleet.add_replica("r0", fake_engine())
        fleet.add_replica("r1", fake_engine())
        # in-flight request pins r0 in quiesce -> RELOADING persists
        fleet.submit(np.array([1, 2, 3]), 50, request_id="long")
        assert fleet.start_rollout(Source(), step=5, grace_s=60.0)
        fleet.tick()
        fs = fleet.snapshot()
        assert fs.replicas["r0"].state == "reloading"
        assert fs.replicas_reloading == 1
        # a reloading replica still reports its real engine load
        assert fs.replicas["r0"].live_requests == 1
        fleet.run_until_drained()

    def test_post_recreate_back_to_serving(self):
        fleet = ServingFleet()
        fleet.add_replica("r0", fake_engine())
        fleet.kill_replica("r0", "replica-lost:test")
        assert fleet.snapshot().replicas_down == 1
        fleet.revive_replica("r0", fake_engine(), step=3)
        fs = fleet.snapshot()
        assert fs.replicas_down == 0
        assert fs.replicas["r0"].state == "serving"
        assert fs.replicas["r0"].down_cause == ""


# -- SloTargets validation -----------------------------------------------------


class TestSloTargets:
    def test_all_disabled_rejected(self):
        with pytest.raises(ValueError, match="grades nothing"):
            SloTargets()

    def test_negative_target_rejected(self):
        with pytest.raises(ValueError, match="ttft_p99_s"):
            SloTargets(ttft_p99_s=-1)

    def test_shed_rate_fraction(self):
        with pytest.raises(ValueError, match="fraction"):
            SloTargets(shed_rate=1.5)

    def test_window_ordering(self):
        with pytest.raises(ValueError, match="short_window"):
            SloTargets(ttft_p99_s=1, short_window=8, long_window=4)

    def test_burn_fractions(self):
        with pytest.raises(ValueError, match="pressured_burn"):
            SloTargets(ttft_p99_s=1, pressured_burn=0.0)

    def test_serve_config_parse_path(self):
        from tpu_nexus.workload.serve import ServeConfig

        cfg = ServeConfig.from_env(
            {"NEXUS_SLO_TTFT_S": "0.5", "NEXUS_SLO_SHORT_N": "2",
             "NEXUS_SLO_LONG_N": "6"}
        )
        t = cfg.slo_targets()
        assert t.ttft_p99_s == 0.5 and t.short_window == 2 and t.long_window == 6
        assert ServeConfig.from_env({}).slo_targets() is None
        with pytest.raises(ValueError, match="short_window"):
            ServeConfig.from_env(
                {"NEXUS_SLO_TTFT_S": "0.5", "NEXUS_SLO_SHORT_N": "9",
                 "NEXUS_SLO_LONG_N": "3"}
            )
        # targets without the cadence that drives observation: the parse
        # refuses — a requested monitor that would silently never grade
        # is a config bug, not a quiet run
        with pytest.raises(ValueError, match="NEXUS_HEARTBEAT_EVERY"):
            ServeConfig.from_env(
                {"NEXUS_SLO_TTFT_S": "0.5", "NEXUS_HEARTBEAT_EVERY": "0"}
            )


# -- SloMonitor ----------------------------------------------------------------


class TestSloMonitor:
    def test_taxonomy_total_at_runtime(self):
        assert set(PRESSURE_SEVERITY) == set(PRESSURE_STATES)
        assert set(PRESSURE_ACTIONS) == set(PRESSURE_STATES)
        assert worst_pressure([PRESSURE_HEALTHY, PRESSURE_SATURATED]) == (
            PRESSURE_SATURATED
        )
        with pytest.raises(KeyError):
            worst_pressure(["mystery"])

    def test_escalation_ladder_and_recovery(self):
        mon = SloMonitor(targets())
        bad, good = snap(ttft_p99_s=0.5), snap(ttft_p99_s=0.001)
        # first violating observation: short burn 1.0 -> PRESSURED
        trs = mon.observe(fleet_of(bad))
        assert [(t["scope"], t["to"]) for t in trs] == [
            ("r0", PRESSURE_PRESSURED), ("fleet", PRESSURE_PRESSURED)
        ]
        # cannot saturate before the long window is FULL (burn-rate
        # confirmation by design)
        mon.observe(fleet_of(bad))
        mon.observe(fleet_of(bad))
        assert mon.grades["r0"] == PRESSURE_PRESSURED
        trs = mon.observe(fleet_of(bad))  # long window now full
        assert mon.grades["r0"] == PRESSURE_SATURATED
        assert any(
            t["scope"] == "r0" and t["to"] == PRESSURE_SATURATED
            and t["action"] == "record+dump" for t in trs
        )
        # recovery: violations age out of the windows
        for _ in range(4):
            mon.observe(fleet_of(good))
        assert mon.grades["r0"] == PRESSURE_HEALTHY
        assert mon.grades["fleet"] == PRESSURE_HEALTHY

    def test_one_blip_does_not_saturate(self):
        mon = SloMonitor(targets(short_window=2, long_window=6))
        bad, good = snap(ttft_p99_s=0.5), snap(ttft_p99_s=0.001)
        for s in (good, good, bad, good, good, good, good):
            mon.observe(fleet_of(s))
        assert mon.grades["r0"] == PRESSURE_HEALTHY
        assert all(t["to"] != PRESSURE_SATURATED for t in mon.transitions)

    def test_tpot_and_shed_dimensions(self):
        mon = SloMonitor(SloTargets(tpot_p99_s=0.01, shed_rate=0.2,
                                    short_window=1, long_window=2))
        trs = mon.observe(fleet_of(snap(tpot_p99_s=0.5)))
        assert trs and trs[0]["violated"] == ["tpot"]
        # shed deltas: 10 sheds vs 2 retirements since last observation
        mon.observe(fleet_of(snap(shed_total=0, requests_retired=0)))
        trs = mon.observe(fleet_of(snap(shed_total=10, requests_retired=2)))
        assert any("shed" in t.get("violated", ()) for t in mon.transitions)

    def test_shed_first_observation_seeds_baseline_only(self):
        # a monitor attached to an already-WARM engine sees since-boot
        # counters on its first observation — that seeds the delta
        # baseline, it is not one interval's worth of sheds
        mon = SloMonitor(SloTargets(shed_rate=0.02, short_window=1, long_window=4))
        trs = mon.observe(
            fleet_of(snap(shed_total=500, requests_retired=10_000))
        )
        assert mon.grades["r0"] == PRESSURE_HEALTHY
        assert not any(t["scope"] == "r0" for t in trs)
        # the NEXT interval's delta grades normally
        mon.observe(fleet_of(snap(shed_total=510, requests_retired=10_010)))
        assert mon.grades["r0"] == PRESSURE_PRESSURED

    def test_down_clears_history_and_bumps_fleet(self):
        mon = SloMonitor(targets())
        bad = snap(ttft_p99_s=0.5)
        ok1 = snap(replica="r1", ttft_p99_s=0.001)
        for _ in range(4):
            mon.observe(fleet_of(bad, ok1))
        assert mon.grades["r0"] == PRESSURE_SATURATED
        # r0 dies: graded DOWN, history cleared; fleet at least PRESSURED
        # (lost capacity) even though the survivor is healthy
        mon.observe(fleet_of(LoadSnapshot.down("r0", "killed"), ok1))
        assert mon.grades["r0"] == PRESSURE_DOWN
        assert mon.grades["r1"] == PRESSURE_HEALTHY
        assert mon.grades["fleet"] == PRESSURE_PRESSURED
        # recreate: fresh engine, fresh grading — healthy immediately,
        # nothing inherited from the dead incarnation's burn history
        mon.observe(fleet_of(snap(ttft_p99_s=0.001), ok1))
        assert mon.grades["r0"] == PRESSURE_HEALTHY
        assert mon.grades["fleet"] == PRESSURE_HEALTHY

    def test_all_down_is_fleet_down(self):
        mon = SloMonitor(targets())
        trs = mon.observe(fleet_of(LoadSnapshot.down("r0", "x")))
        assert mon.grades["fleet"] == PRESSURE_DOWN
        assert any(t["scope"] == "fleet" and t["to"] == PRESSURE_DOWN for t in trs)

    def test_removed_replica_forgotten(self):
        mon = SloMonitor(targets())
        mon.observe(fleet_of(snap(), snap(replica="r1")))
        assert "r1" in mon.grades
        mon.observe(fleet_of(snap()))
        assert "r1" not in mon.grades

    def test_pressure_metrics_emitted(self):
        rec = RecordingMetrics()
        mon = SloMonitor(targets(), metrics=rec)
        mon.observe(fleet_of(snap(ttft_p99_s=0.5)))
        assert rec.gauges["fleet.pressure_level"] == PRESSURE_SEVERITY[
            PRESSURE_PRESSURED
        ]
        key = (
            "fleet.pressure_transitions",
            ("from:healthy", "scope:r0", "to:pressured"),
        )
        assert rec.tagged_counts[key] == 1

    def test_transitions_log_bounded(self):
        mon = SloMonitor(targets(short_window=1, long_window=1,
                                 saturated_burn=1.0),
                         transitions_limit=8)
        bad, good = snap(ttft_p99_s=0.5), snap(ttft_p99_s=0.001)
        for i in range(40):
            mon.observe(fleet_of(bad if i % 2 else good))
        assert len(mon.transitions) == 8


# -- identity gates: observation never perturbs the stream ---------------------


IDENT_CFG = LlamaConfig.tiny()
IDENT_PARAMS = llama_init(jax.random.PRNGKey(0), IDENT_CFG)
IDENT_PROMPTS = [
    np.random.default_rng(5).integers(1, 256, size=n).astype(np.int32)
    for n in (4, 6, 8, 5)
]


def _drain_with_monitor(engine, monitor=None):
    reqs = [
        engine.submit(p, 6, request_id=f"r{i}")
        for i, p in enumerate(IDENT_PROMPTS)
    ]
    while engine.has_work:
        engine.step()
        if monitor is not None:
            s = dataclasses.replace(engine.load_snapshot(), replica="e")
            monitor.observe(FleetSnapshot.aggregate({"e": s}))
    assert all(r.state == RequestState.FINISHED for r in reqs)
    return {r.request_id: list(r.output_tokens) for r in reqs}


class TestMonitorIdentity:
    """Token streams with a per-step SloMonitor observation must be
    IDENTICAL to unobserved runs — the snapshot path reads materialized
    host state only (NX014), and these runs are the behavioral proof."""

    @pytest.mark.parametrize(
        "mode", ["contiguous", "paged", "overlap", "int8kv"]
    )
    def test_single_chip_modes(self, mode):
        kwargs = dict(num_slots=2, max_len=16)
        def build():
            if mode == "paged":
                ex = PagedModelExecutor(
                    IDENT_PARAMS, IDENT_CFG, page_size=4, **kwargs
                )
                return ServingEngine(ex)
            if mode == "int8kv":
                ex = ModelExecutor(
                    IDENT_PARAMS, IDENT_CFG, kv_quant="int8", **kwargs
                )
                return ServingEngine(ex)
            if mode == "overlap":
                ex = ModelExecutor(
                    IDENT_PARAMS, IDENT_CFG, decode_steps=2, **kwargs
                )
                return ServingEngine(ex, overlap=True)
            return ServingEngine(ModelExecutor(IDENT_PARAMS, IDENT_CFG, **kwargs))

        # aggressive targets: the monitor GRADES (transitions fire), it
        # just must not touch the stream
        monitored = _drain_with_monitor(
            build(), SloMonitor(targets(ttft_p99_s=1e-9, short_window=1,
                                        long_window=2))
        )
        plain = _drain_with_monitor(build(), None)
        assert monitored == plain

    def test_sharded_mode(self):
        from tpu_nexus.serving import ShardedModelExecutor, build_serve_mesh

        cfg = LlamaConfig(
            vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=4,
            head_dim=16, intermediate=128, max_seq_len=256, remat=False,
            dtype=jnp.float32, param_dtype=jnp.float32,
        )
        params = llama_init(jax.random.PRNGKey(0), cfg)

        def build():
            ex = ShardedModelExecutor(
                params, cfg, mesh=build_serve_mesh({"tp": 2}),
                num_slots=2, max_len=16,
            )
            return ServingEngine(ex)

        monitored = _drain_with_monitor(
            build(), SloMonitor(targets(ttft_p99_s=1e-9, short_window=1,
                                        long_window=2))
        )
        plain = _drain_with_monitor(build(), None)
        assert monitored == plain


# -- the saturation chaos drill ------------------------------------------------


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class TestSaturationDrill:
    """Injected slow-step faults drive one replica of a supervised fleet
    HEALTHY -> PRESSURED -> SATURATED: the transition lands as
    cause+details JSON on the fleet's RUNNING ledger row, and the
    saturated replica's flight recorder dumps at the saturation seam."""

    def test_slow_step_escalates_with_ledger_and_dump(self, tmp_path):
        from tpu_nexus.serving.tracing import EngineTracer, FlightRecorder

        store = InMemoryCheckpointStore()
        fleet = ServingFleet()
        # r0: every decode step delayed 30ms through the REAL chaos
        # boundary (workload/faults.FaultyExecutor slow-step mode)
        slow = FaultyExecutor(
            FakeExecutor(2, 256), "slow-step", at_step=0, slow_s=0.03
        )
        eng0 = ServingEngine(
            slow,
            tracer=EngineTracer(
                recorder=FlightRecorder(dump_dir=str(tmp_path))
            ),
        )
        fleet.add_replica("r0", eng0)
        fleet.add_replica("r1", fake_engine(max_len=256))
        sup = FleetSupervisor(
            FakeKubeClient(),
            store,
            NS,
            fleet,
            FLEET_JS,
            ALGO,
            lambda name, step, kv: fake_engine(),
            slo=SloMonitor(
                SloTargets(tpot_p99_s=0.005, short_window=2, long_window=4)
            ),
        )
        # all traffic onto the slow replica directly: the fleet ticks its
        # engines sequentially in one thread, so r0's injected sleeps
        # would stretch wall time between r1's tokens too and smear the
        # fault across replicas — the idle-r1 assertion below is the
        # blast-radius check (slowness on r0 grades ONLY r0)
        for i in range(8):
            eng0.submit(np.array([1, 2, 3]), 200, request_id=f"q{i}")

        seen = []
        async def drive():
            for _ in range(8):
                await sup.reconcile()
                seen.append(sup.slo.grades.get("r0", PRESSURE_HEALTHY))
                if sup.slo.grades.get("r0") == PRESSURE_SATURATED:
                    break

        _run(drive())
        # the ladder: healthy start, pressured detection, saturated
        # confirmation — in that order
        assert seen[-1] == PRESSURE_SATURATED, seen
        assert PRESSURE_PRESSURED in seen
        tos = [t["to"] for t in sup.pressure_events if t["scope"] == "r0"]
        assert tos == [PRESSURE_PRESSURED, PRESSURE_SATURATED]
        # the healthy replica never degrades
        assert sup.slo.grades["r1"] == PRESSURE_HEALTHY
        # ledger: RUNNING row carrying the pressure cause + graded details
        cp = store.read_checkpoint(ALGO, FLEET_JS)
        assert "fleet pressure: " in cp.algorithm_failure_cause
        details = json.loads(cp.algorithm_failure_details)
        assert details["pressure"]["to"] in (
            PRESSURE_PRESSURED, PRESSURE_SATURATED
        )
        assert details["grades"]["r0"] == PRESSURE_SATURATED
        assert details["fleet"]["replicas"]["r0"]["state"] == "serving"
        # the saturation dump: recorded on the event AND on disk, naming
        # the seam
        sat = next(t for t in sup.pressure_events if t["to"] == PRESSURE_SATURATED)
        assert sat["flight_recorder"]["reason"] == (
            "saturation:slo-saturated:r0"
        )
        dump_path = sat["flight_recorder"]["path"]
        with open(dump_path, "r", encoding="utf-8") as fh:
            artifact = json.load(fh)
        assert artifact["seam"] == "saturation"
        assert artifact["implicated_total"] > 0
        assert eng0.metrics.trace_dumps_total == 1

    def test_down_replica_graded_down_via_supervisor(self):
        store = InMemoryCheckpointStore()
        fleet = ServingFleet()
        fleet.add_replica("r0", fake_engine())
        fleet.add_replica("r1", fake_engine())
        sup = FleetSupervisor(
            FakeKubeClient(), store, NS, fleet, FLEET_JS, ALGO,
            lambda name, step, kv: fake_engine(),
            slo=SloMonitor(targets()),
        )
        # an incident record already on the books: the pressure write that
        # follows shares the cause/details columns and must CARRY it, not
        # clobber it off the row
        sup.incidents.append(
            {"cause": "replica-lost:test", "replica": "r0", "action": "recreate"}
        )

        async def drive():
            await sup.reconcile()
            fleet.kill_replica("r0", "replica-lost:test")
            await sup.reconcile()

        _run(drive())
        assert sup.slo.grades["r0"] == PRESSURE_DOWN
        assert sup.slo.grades["fleet"] == PRESSURE_PRESSURED
        assert any(
            t["scope"] == "r0" and t["to"] == PRESSURE_DOWN
            for t in sup.pressure_events
        )
        cp = store.read_checkpoint(ALGO, FLEET_JS)
        assert cp.algorithm_failure_cause.startswith("fleet pressure: ")
        details = json.loads(cp.algorithm_failure_details)
        assert details["incidents"][-1]["cause"] == "replica-lost:test"

    def test_pressure_events_log_bounded(self):
        # a replica flapping around its SLO target transitions for the
        # supervisor's lifetime — the event log front-trims at the limit
        # (the SloMonitor.transitions discipline)
        fleet = ServingFleet()
        fleet.add_replica("r0", fake_engine())
        sup = FleetSupervisor(
            FakeKubeClient(), InMemoryCheckpointStore(), NS, fleet,
            FLEET_JS, ALGO, lambda name, step, kv: fake_engine(),
            slo=SloMonitor(targets(short_window=1, long_window=1)),
        )
        sup._pressure_events_limit = 3

        class FlappingMonitor:
            grades = {}
            def observe(self, snapshot):
                return [
                    {"scope": "ghost", "from": PRESSURE_HEALTHY,
                     "to": PRESSURE_PRESSURED, "action": "record", "t": 0.0},
                ]
            def summary(self):
                return {}

        sup.slo = FlappingMonitor()

        async def drive():
            for _ in range(8):
                await sup.reconcile()

        _run(drive())
        assert len(sup.pressure_events) == 3


# -- serve-loop integration ----------------------------------------------------


class TestServeLoopPressure:
    def test_summary_and_ledger_carry_snapshot_and_grade(self):
        from tpu_nexus.checkpoint.models import LifecycleStage
        from tpu_nexus.parallel.distributed import ProcessContext
        from tpu_nexus.workload.serve import ServeConfig, run_serve_engine

        store = InMemoryCheckpointStore()
        ctx = ProcessContext(
            algorithm="serve-algo", run_id="slo-run", process_id=0,
            num_processes=1, coordinator="",
        )
        cfg = ServeConfig(
            model=LlamaConfig.tiny(), batch_size=2, prompt_len=8,
            gen_tokens=4, rounds=2, heartbeat_every=1,
            slo_ttft_s=10.0, slo_short_window=1, slo_long_window=2,
        )
        out = run_serve_engine(cfg, store=store, ctx=ctx)
        assert out["pressure"]["grades"]["engine"] == PRESSURE_HEALTHY
        # + 1: the warmup request retires on the same engine
        assert out["load_snapshot"]["requests_retired"] == out["requests"] + 1
        cp = store.read_checkpoint("serve-algo", "slo-run")
        assert cp.lifecycle_stage == LifecycleStage.COMPLETED
        details = json.loads(cp.algorithm_failure_details)
        assert "load_snapshot" in details
        assert details["pressure"]["grades"]["engine"] == PRESSURE_HEALTHY


# -- goodput -------------------------------------------------------------------


class TestGoodputMeter:
    def test_buckets_sum_to_elapsed_property(self):
        # property test: random lap sequences over a fake clock — the
        # buckets must sum to elapsed EXACTLY up to float accumulation
        rng = np.random.default_rng(7)
        for trial in range(50):
            t = [0.0]

            def clock():
                return t[0]

            meter = GoodputMeter(clock=clock)
            meter.start()
            for _ in range(int(rng.integers(1, 40))):
                t[0] += float(rng.uniform(0, 3.0))
                meter.lap(str(rng.choice(BUCKETS)))
            t[0] += float(rng.uniform(0, 1.0))  # residual -> host_other
            meter.stop()
            total = sum(meter.buckets.values())
            assert math.isclose(
                total, meter.elapsed_s, rel_tol=1e-9, abs_tol=1e-9
            ), (trial, total, meter.elapsed_s)

    def test_real_clock_bucket_sum(self):
        meter = GoodputMeter()
        meter.start()
        for bucket in (BUCKET_DATA, BUCKET_STEP, BUCKET_STEP, BUCKET_OTHER):
            meter.lap(bucket)
        meter.stop()
        assert math.isclose(
            sum(meter.buckets.values()), meter.elapsed_s,
            rel_tol=1e-9, abs_tol=1e-9,
        )

    def test_misuse_raises(self):
        meter = GoodputMeter()
        with pytest.raises(RuntimeError, match="before start"):
            meter.lap(BUCKET_STEP)
        meter.start()
        with pytest.raises(RuntimeError, match="twice"):
            meter.start()
        with pytest.raises(KeyError):
            meter.lap("not-a-bucket")

    def test_stop_idempotent(self):
        t = [0.0]
        meter = GoodputMeter(clock=lambda: t[0])
        meter.start()
        t[0] = 5.0
        meter.stop()
        t[0] = 9.0
        meter.stop()
        assert meter.elapsed_s == 5.0
        assert meter.buckets[BUCKET_OTHER] == 5.0

    def test_derived_numbers(self):
        t = [0.0]
        meter = GoodputMeter(
            clock=lambda: t[0], flops_per_token=100.0, peak_flops=1000.0
        )
        meter.start()
        t[0] = 6.0
        meter.lap(BUCKET_STEP)
        t[0] = 10.0
        meter.lap(BUCKET_OTHER)
        meter.note_step(20)
        meter.note_step(20)
        meter.stop()
        assert meter.productive_fraction() == 0.6
        assert meter.tokens_per_second() == 4.0
        assert meter.mfu() == pytest.approx(4.0 * 100.0 / 1000.0)
        s = meter.summary()
        assert s["steps"] == 2 and s["tokens"] == 40
        assert "step_dispatch" in meter.table()
        rec = RecordingMetrics()
        meter.gauges(rec)
        assert rec.gauges["train.goodput"] == 0.6
        assert rec.gauges["train.mfu"] == pytest.approx(0.4)

    def test_null_meter_surface(self):
        meter = NullGoodputMeter()
        meter.start(); meter.lap("whatever"); meter.note_step(5); meter.stop()
        assert meter.summary() == {} and meter.table() == ""
        assert not meter.enabled


class TestFlopsEstimator:
    def test_dense_matches_hand_computation(self):
        cfg = LlamaConfig.tiny()
        e, f = cfg.hidden, cfg.intermediate
        hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        l, v, seq = cfg.n_layers, cfg.vocab_size, 32
        ffn = 3 * e * f
        params = l * (e * hq * d + 2 * e * hkv * d + hq * d * e + ffn) + e * v
        expected = 3.0 * (2.0 * params + 2 * seq * hq * d * l)
        assert model_flops_per_token(cfg, seq) == expected

    def test_moe_counts_active_params_only(self):
        cfg = MoeConfig.tiny()
        per_tok = model_flops_per_token(cfg, 32)
        dense_equiv = dataclasses.replace(cfg, n_experts=0)
        # top-2 of 4 experts: active ffn ~2x one expert's, far below 4x
        assert per_tok > 0
        e, f = cfg.hidden, cfg.intermediate
        # the ffn term must reflect experts_per_token, not n_experts
        active_ffn = cfg.experts_per_token * 3 * e * f + e * cfg.n_experts
        all_ffn = cfg.n_experts * 3 * e * f
        assert active_ffn < all_ffn
        delta = model_flops_per_token(cfg, 32) - model_flops_per_token(
            dataclasses.replace(cfg, n_experts=0), 32
        )
        # swapping dense ffn (3ef) for active moe ffn changes exactly that term
        assert delta == pytest.approx(3.0 * 2.0 * cfg.n_layers * (active_ffn - 3 * e * f))

    def test_non_transformer_config_is_zero(self):
        class Mnist:
            pass

        assert model_flops_per_token(Mnist(), 32) == 0.0

    def test_peak_lookup(self):
        class Dev:
            device_kind = "TPU v5 lite"

        assert chip_peak_flops(Dev(), env={}) == 197.0e12
        assert chip_peak_flops(Dev(), env={"NEXUS_PEAK_TFLOPS": "100"}) == 1e14

        class Cpu:
            device_kind = "cpu"

        assert chip_peak_flops(Cpu(), env={}) == 0.0


class TestGoodputInHarness:
    def _cfg(self, goodput, **over):
        from tpu_nexus.parallel import MeshSpec
        from tpu_nexus.workload.harness import WorkloadConfig
        from tpu_nexus.workload.health import HealthConfig

        base = dict(
            model=LlamaConfig.tiny(),
            mesh=MeshSpec(),
            batch_size=2,
            seq_len=32,
            steps=4,
            heartbeat_every=2,
            health=HealthConfig(enabled=False),
            goodput=goodput,
        )
        base.update(over)
        from tpu_nexus.workload.harness import WorkloadConfig

        return WorkloadConfig(**base)

    def test_goodput_on_vs_off_loss_bit_identical(self):
        from tpu_nexus.workload.harness import run_workload

        on = run_workload(self._cfg(True))
        off = run_workload(self._cfg(False))
        assert on["loss"] == off["loss"]  # bit-identical, not approx
        assert on["final_step"] == off["final_step"] == 4
        assert "goodput" not in off
        g = on["goodput"]
        assert g["steps"] == 4 and g["tokens"] == 4 * 2 * 32
        assert math.isclose(
            sum(g["buckets_s"].values()), g["elapsed_s"],
            rel_tol=1e-6, abs_tol=1e-4,
        )
        # first-iteration compile is startup, not steady state
        assert g["buckets_s"][BUCKET_INIT] > g["buckets_s"][BUCKET_STEP] * 0.0
        assert g["buckets_s"][BUCKET_INIT] > 0
        assert 0.0 < g["productive_fraction"] < 1.0
        assert g["mfu"] == 0.0  # unknown CPU peak: 0, never a fabrication

    def test_terminal_details_carry_goodput_heartbeat_map_stays_clean(self):
        from tpu_nexus.parallel.distributed import ProcessContext
        from tpu_nexus.workload.harness import run_workload

        store = InMemoryCheckpointStore()
        ctx = ProcessContext(
            algorithm="algo", run_id="gp-run", process_id=0,
            num_processes=1, coordinator="",
        )
        run_workload(self._cfg(True), store=store, ctx=ctx)
        cp = store.read_checkpoint("algo", "gp-run")
        # per_chip_steps means per-CHIP step counters (watchdog staleness
        # signature, on-call queries) — goodput must NOT pollute the map
        assert all(k.startswith("host") for k in cp.per_chip_steps)
        # the goodput story lands in the terminal COMPLETED details
        details = json.loads(cp.algorithm_failure_details)
        g = details["goodput"]
        assert g["steps"] == 4 and g["tokens"] == 4 * 2 * 32
        assert 0.0 < g["productive_fraction"] < 1.0
        assert set(g["buckets_s"]) == set(BUCKETS)
        # goodput-off: no details written at all (seed behavior)
        off_store = InMemoryCheckpointStore()
        off_ctx = ProcessContext(
            algorithm="algo", run_id="gp-off", process_id=0,
            num_processes=1, coordinator="",
        )
        run_workload(self._cfg(False), store=off_store, ctx=off_ctx)
        off_cp = off_store.read_checkpoint("algo", "gp-off")
        assert off_cp.algorithm_failure_details == ""

    def test_checkpoint_time_lands_in_checkpoint_bucket(self, tmp_path):
        from tpu_nexus.workload.goodput import BUCKET_CKPT
        from tpu_nexus.workload.harness import run_workload

        out = run_workload(
            self._cfg(
                True, checkpoint_every=2, checkpoint_dir=str(tmp_path)
            )
        )
        assert out["goodput"]["buckets_s"][BUCKET_CKPT] > 0.0
