"""RestKubeClient + SharedInformerFactory against a REAL kube-apiserver.

The r2 verdict's missing item #2: the hand-rolled LIST/WATCH/resourceVersion
plane (k8s/rest.py, k8s/informer.py) had only ever met an aiohttp loopback
stub; the reference gets the apiserver contract for free from client-go
(services/supervisor.go:16-18,71-75).  This suite drives the real contract:
list -> watch -> event delivery -> delete (background propagation) -> watch
DELETED -> informer relist repair, against envtest-style control-plane
binaries (`etcd` + `kube-apiserver`).

Gating mirrors the real-Scylla suite: the tests SKIP with a reason unless
the binaries are found (KUBEBUILDER_ASSETS — `setup-envtest use -p path` —
or $PATH), and NEXUS_REQUIRE_APISERVER=1 turns a skip into a failure so CI
runners that provision the binaries cannot silently lose the coverage.
410-Gone mid-stream and split-frame decoding are deterministic against the
protocol stub in test_k8s_rest.py; here the same informer loop runs against
the genuine apiserver implementation (chunked frames, bookmarks, real
resourceVersion discipline).
"""

import asyncio
import json
import os
import shutil
import socket
import subprocess
import time

import pytest

KUBE_ASSETS = os.environ.get("KUBEBUILDER_ASSETS", "")


def _find(binary: str):
    if KUBE_ASSETS:
        cand = os.path.join(KUBE_ASSETS, binary)
        if os.path.exists(cand):
            return cand
    return shutil.which(binary)


ETCD = _find("etcd")
APISERVER = _find("kube-apiserver")
HAVE_BINARIES = bool(ETCD and APISERVER)

if os.environ.get("NEXUS_REQUIRE_APISERVER") == "1" and not HAVE_BINARIES:
    pytest.fail(
        "NEXUS_REQUIRE_APISERVER=1 but etcd/kube-apiserver binaries not found "
        "(set KUBEBUILDER_ASSETS, e.g. via `setup-envtest use -p path`)",
        pytrace=False,
    )

pytestmark = pytest.mark.skipif(
    not HAVE_BINARIES,
    reason="etcd + kube-apiserver binaries not available "
    "(install envtest binaries and set KUBEBUILDER_ASSETS to enable)",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


TOKEN = "nexus-apiserver-test-token"


@pytest.fixture(scope="module")
def apiserver(tmp_path_factory):
    """etcd + kube-apiserver with static-token auth, torn down after the
    module.  Yields the https base URL."""
    root = tmp_path_factory.mktemp("apiserver")
    etcd_port, etcd_peer = _free_port(), _free_port()
    api_port = _free_port()

    procs = []

    def _teardown():
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)

    try:
        procs.append(subprocess.Popen(
            [
                ETCD,
                "--data-dir", str(root / "etcd"),
                "--listen-client-urls", f"http://127.0.0.1:{etcd_port}",
                "--advertise-client-urls", f"http://127.0.0.1:{etcd_port}",
                "--listen-peer-urls", f"http://127.0.0.1:{etcd_peer}",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        ))

        sa_key = root / "sa.key"
        subprocess.run(
            ["openssl", "genrsa", "-out", str(sa_key), "2048"],
            check=True, capture_output=True,
        )
        tokens = root / "tokens.csv"
        tokens.write_text(f"{TOKEN},nexus-admin,nexus-admin-uid,system:masters\n")

        procs.append(subprocess.Popen(
            [
                APISERVER,
                "--etcd-servers", f"http://127.0.0.1:{etcd_port}",
                "--secure-port", str(api_port),
                "--cert-dir", str(root / "certs"),  # self-signed serving certs
                "--token-auth-file", str(tokens),
                "--authorization-mode", "AlwaysAllow",
                "--service-account-issuer", "https://kubernetes.default.svc",
                "--service-account-signing-key-file", str(sa_key),
                "--service-account-key-file", str(sa_key),
                "--disable-admission-plugins", "ServiceAccount",
                "--watch-cache=true",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        ))

        base = f"https://127.0.0.1:{api_port}"
        _wait_ready(base, timeout=60)
        yield base
    finally:
        _teardown()


def _wait_ready(base: str, timeout: float) -> None:
    import ssl
    import urllib.request

    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            req = urllib.request.Request(
                f"{base}/readyz", headers={"Authorization": f"Bearer {TOKEN}"}
            )
            with urllib.request.urlopen(req, context=ctx, timeout=2) as resp:
                if resp.status == 200:
                    return
        except Exception as exc:  # noqa: BLE001 - retry until deadline
            last = exc
        time.sleep(0.5)
    raise RuntimeError(f"kube-apiserver not ready in {timeout}s: {last!r}")


def _client(base: str):
    import ssl

    from tpu_nexus.k8s.rest import RestKubeClient

    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE  # self-signed serving cert
    return RestKubeClient(base, token=TOKEN, ssl_context=ctx)


def _job(name: str, ns: str = "default"):
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "template": {
                "metadata": {"labels": {"job-name": name}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [{"name": "main", "image": "busybox", "command": ["true"]}],
                },
            },
        },
    }


async def _drive_list_watch_delete(base: str):
    client = _client(base)
    try:
        items, rv = await client.list_objects("Job", "default")
        assert rv, "LIST must return a resourceVersion"
        baseline = {i["metadata"]["name"] for i in items}

        seen = asyncio.Queue()

        async def watcher():
            async for et, obj in client.watch_objects("Job", "default", rv):
                if et == "BOOKMARK":
                    continue
                await seen.put((et, obj["metadata"]["name"]))

        wtask = asyncio.create_task(watcher())
        try:
            await client.create_object("Job", "default", _job("nexus-it-1"))
            et, name = await asyncio.wait_for(seen.get(), timeout=30)
            assert (et, name) == ("ADDED", "nexus-it-1")
            assert "nexus-it-1" not in baseline

            await client.delete_object("Job", "default", "nexus-it-1")
            # background propagation: DELETED arrives once finalizers clear
            deadline = asyncio.get_running_loop().time() + 30
            got_delete = False
            while asyncio.get_running_loop().time() < deadline:
                et, name = await asyncio.wait_for(seen.get(), timeout=30)
                if name == "nexus-it-1" and et == "DELETED":
                    got_delete = True
                    break
            assert got_delete, "watch must deliver DELETED for the removed Job"
        finally:
            wtask.cancel()
            try:
                await wtask
            except asyncio.CancelledError:
                pass
    finally:
        await client.close()


def test_list_watch_create_delete_roundtrip(apiserver):
    """The supervisor's exact I/O pattern against the real server: LIST with
    rv, WATCH from rv (chunked frames from the real apiserver), CREATE seen
    as ADDED, DELETE (background propagation) seen as DELETED."""
    asyncio.run(_drive_list_watch_delete(apiserver))


async def _drive_informer(base: str):
    from datetime import timedelta

    from tpu_nexus.core.signals import LifecycleContext
    from tpu_nexus.k8s.informer import SharedInformerFactory

    client = _client(base)
    try:
        await client.create_object("Job", "default", _job("nexus-it-pre"))
        factory = SharedInformerFactory(
            client, "default", resync_period=timedelta(seconds=2)
        )
        informer = factory.informer_for("Job")
        events = []
        informer.add_event_handler(lambda et, obj: events.append((et, obj.meta.name)))
        ctx = LifecycleContext()
        factory.start(ctx)
        assert await factory.wait_for_cache_sync(timeout=30)
        assert informer.get("nexus-it-pre") is not None  # initial LIST seeded

        await client.create_object("Job", "default", _job("nexus-it-live"))
        deadline = asyncio.get_running_loop().time() + 30
        while asyncio.get_running_loop().time() < deadline:
            if ("ADDED", "nexus-it-live") in events and informer.get("nexus-it-live"):
                break
            await asyncio.sleep(0.05)
        assert ("ADDED", "nexus-it-live") in events, events

        # survive at least one resync relist (period 2s) without phantom
        # ADDED/DELETED churn for unchanged objects
        n_before = len([e for e in events if e[1] == "nexus-it-pre"])
        await asyncio.sleep(3)
        n_after = len([e for e in events if e[1] == "nexus-it-pre"])
        assert n_after == n_before, "resync relist must not re-deliver unchanged objects"

        ctx.cancel()
        await factory.shutdown()
        for name in ("nexus-it-pre", "nexus-it-live"):
            await client.delete_object("Job", "default", name)
    finally:
        await client.close()


def test_informer_against_real_apiserver(apiserver):
    """SharedInformerFactory end to end on the real watch stream: cache
    seeding, live event delivery, and resync relists that stay quiet for
    unchanged objects."""
    asyncio.run(_drive_informer(apiserver))
