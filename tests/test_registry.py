"""Model registry: every zoo model runs the same harness/train-step/ledger
contract (VERDICT r1 missing #5 — BASELINE config #3: MNIST demo workload,
classify an injected XLA compile abort)."""

import asyncio
import uuid
from datetime import timedelta

import jax
import jax.numpy as jnp
import pytest

from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
from tpu_nexus.models import (
    LlamaAdapter,
    LlamaConfig,
    MnistAdapter,
    MnistConfig,
    adapter_for,
    get_adapter,
)
from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
from tpu_nexus.parallel.distributed import ProcessContext
from tpu_nexus.workload.harness import WorkloadConfig, run_workload
from tpu_nexus.workload.train import TrainConfig, init_train_state, make_train_step


class TestRegistry:
    def test_adapter_dispatch(self):
        assert isinstance(adapter_for(LlamaConfig.tiny()), LlamaAdapter)
        assert isinstance(adapter_for(MnistConfig()), MnistAdapter)
        adapter = MnistAdapter()
        assert adapter_for(adapter) is adapter
        with pytest.raises(TypeError):
            adapter_for(object())

    def test_preset_lookup(self):
        assert isinstance(get_adapter("mnist"), MnistAdapter)
        assert get_adapter("tiny").config == LlamaConfig.tiny()
        assert get_adapter("nexus_1b").config == LlamaConfig.nexus_1b()
        # 32k single-chip long-context preset (PERF.md r3): same weights
        # shape as nexus_1b, stretched window
        long_cfg = get_adapter("nexus_1b_long").config
        assert long_cfg.max_seq_len == 32768
        assert long_cfg.hidden == LlamaConfig.nexus_1b().hidden
        with pytest.raises(KeyError, match="known"):
            get_adapter("nope")

    def test_from_env_selects_mnist(self):
        cfg = WorkloadConfig.from_env({"NEXUS_MODEL_PRESET": "mnist", "NEXUS_STEPS": "5"})
        assert isinstance(cfg.model, MnistAdapter)


class TestMnistTrainStep:
    def test_loss_decreases_and_accuracy_rises_sharded(self):
        adapter = MnistAdapter()
        tcfg = TrainConfig(warmup_steps=2, total_steps=100, learning_rate=3e-3)
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(jax.random.PRNGKey(0), adapter, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        step_fn = make_train_step(adapter, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        data = adapter.data(32, 0, seed=0)
        losses, accs = [], []
        with mesh:
            for _ in range(30):
                batch = jax.tree.map(jnp.asarray, next(data))
                state, m = step_fn(state, batch)
                losses.append(float(m["loss"]))
                accs.append(float(m["accuracy"]))
        assert losses[-1] < losses[0] - 0.5, losses[:3] + losses[-3:]
        assert accs[-1] > 0.8, accs[-5:]

    def test_mnist_params_sharded(self):
        adapter = MnistAdapter()
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(
            jax.random.PRNGKey(0), adapter, TrainConfig(), mesh, LOGICAL_RULES_FSDP_TP
        )
        w = state["params"]["hidden"]["w"]  # [L, hidden(embed->fsdp), hidden(mlp->tp)]
        shard = w.addressable_shards[0].data
        assert shard.shape[1] == w.shape[1] // 4
        assert shard.shape[2] == w.shape[2] // 2


class TestMnistThroughHarness:
    """BASELINE config #3 end to end: the MNIST demo runs the full harness
    (ledger RUNNING/heartbeat/COMPLETED), and an injected XLA compile abort
    surfaces with a classifiable message + trace ref."""

    def _config(self, **over):
        base = dict(
            model=MnistAdapter(),
            train=TrainConfig(warmup_steps=2, total_steps=50, learning_rate=1e-3),
            mesh=MeshSpec(fsdp=-1),
            batch_size=16,
            seq_len=0,
            steps=8,
            heartbeat_every=2,
        )
        base.update(over)
        return WorkloadConfig(**base)

    def test_clean_run_completes_with_heartbeats(self):
        rid = str(uuid.uuid4())
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm="mnist-train", id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
        )
        ctx = ProcessContext(run_id=rid, algorithm="mnist-train", process_id=0, num_processes=1, coordinator=None)
        summary = run_workload(self._config(), store=store, ctx=ctx)
        assert summary["final_step"] == 8
        assert summary["accuracy"] >= 0.0
        cp = store.read_checkpoint("mnist-train", rid)
        assert cp.lifecycle_stage == LifecycleStage.COMPLETED
        assert cp.per_chip_steps  # heartbeats landed

    def test_injected_xla_abort_classified(self, monkeypatch):
        from tpu_nexus.supervisor.taxonomy import DecisionAction, classify_tpu_failure
        from tpu_nexus.workload.faults import ENV_FAULT_MODE, ENV_FAULT_STEP

        monkeypatch.setenv(ENV_FAULT_MODE, "xla-abort")
        monkeypatch.setenv(ENV_FAULT_STEP, "3")
        rid = str(uuid.uuid4())
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm="mnist-train", id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
        )
        ctx = ProcessContext(run_id=rid, algorithm="mnist-train", process_id=0, num_processes=1, coordinator=None)
        with pytest.raises(RuntimeError, match="hlo_trace") as ei:
            run_workload(self._config(), store=store, ctx=ctx)
        # the raised message is what lands in the pod termination text /
        # k8s event — it must classify as a compile abort
        assert classify_tpu_failure(str(ei.value)) == DecisionAction.TO_FAIL_COMPILE_ABORT
        cp = store.read_checkpoint("mnist-train", rid)
        assert cp.hlo_trace_ref.startswith("file://")


async def test_mnist_xla_abort_supervised_to_failed():
    """Full loop for config #3: the MNIST workload dies with the compile
    abort, its message becomes a pod Failed event, and the supervisor lands
    FAILED + compile-abort cause in the ledger."""
    from tests.test_supervisor import (
        ALGORITHM,
        Fixture,
        event_obj,
        job_obj,
        pod_obj,
        seed_checkpoint,
    )
    from tpu_nexus.supervisor.taxonomy import MSG_COMPILE_ABORT
    from tpu_nexus.workload.faults import MSG_XLA_ABORT

    rid = str(uuid.uuid4())
    pod = pod_obj(rid)
    objects = {
        "Job": [job_obj(rid)],
        "Pod": [pod],
        "Event": [event_obj("Failed", MSG_XLA_ABORT, "Pod", pod["metadata"]["name"])],
    }
    fx = Fixture(objects)
    seed_checkpoint(fx.store, rid, LifecycleStage.RUNNING)
    await fx.run_until_idle()
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.FAILED
    assert cp.algorithm_failure_cause == MSG_COMPILE_ABORT
    assert rid in fx.client.deleted("Job")
