"""Fused decode-attention kernel: parity against the XLA decode path.

The kernel (ops/decode_attention.py) and the masked-einsum fallback in
models/generate.cached_attention are the SAME contract — every shape the
dispatcher can route either way must agree to kernel rounding.  Runs the
pallas interpreter on the CPU mesh; environments whose (old) jax cannot
interpret the kernel skip cleanly rather than fail.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpu_nexus.ops.decode_attention as da
from tpu_nexus.models.generate import _quantize_kv, cached_attention
from tpu_nexus.ops.decode_attention import decode_attention, decode_supported


def _interpret_works() -> bool:
    """Probe once whether this jax can interpret the kernel (old releases
    lack pieces of the pallas interpreter; skip cleanly there)."""
    try:
        q = jnp.ones((1, 1, 2, 8), jnp.float32)
        kv = jnp.ones((1, 16, 2, 8), jnp.float32)
        decode_attention(q, kv, kv, jnp.asarray(4, jnp.int32), interpret=True)
        return True
    except Exception:  # noqa: BLE001 - any interpreter failure means "skip env"
        return False


_CAN_INTERPRET = _interpret_works()

pytestmark = pytest.mark.skipif(
    not _CAN_INTERPRET, reason="pallas interpreter cannot run the decode kernel on this jax"
)


def _xla(q, k, v, kv_len, **kw):
    return cached_attention(q, k, v, kv_len, impl="xla", **kw)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype)


def _case(b=2, hq=4, hkv=2, d=32, max_len=96, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    k = _rand(ks[0], (b, max_len, hkv, d), dtype)
    v = _rand(ks[1], (b, max_len, hkv, d), dtype)
    return k, v


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(rtol=2e-5, atol=2e-5)


class TestKernelParity:
    @pytest.mark.parametrize("sq", [1, 8])
    @pytest.mark.parametrize("hq,hkv", [(4, 2), (2, 2)])  # GQA and MHA
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_uniform_matches_xla(self, sq, hq, hkv, dtype):
        k, v = _case(hq=hq, hkv=hkv, dtype=dtype)
        q = _rand(jax.random.PRNGKey(7), (2, sq, hq, 32), dtype)
        kv_len = jnp.asarray(61, jnp.int32)
        out = decode_attention(q, k, v, kv_len, interpret=True)
        ref = _xla(q, k, v, kv_len)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), **_tol(dtype)
        )

    @pytest.mark.parametrize("sq", [1, 8])
    @pytest.mark.parametrize("hq,hkv", [(4, 2), (2, 2)])
    def test_int8_kv_matches_xla(self, sq, hq, hkv):
        """Native int8-KV reads with in-kernel deferred dequant (k_scale on
        scores, v_scale folded into the weights) vs the XLA identity."""
        k, v = _case(hq=hq, hkv=hkv)
        kq, ksc = _quantize_kv(k)
        vq, vsc = _quantize_kv(v)
        assert kq.dtype == jnp.int8
        q = _rand(jax.random.PRNGKey(8), (2, sq, hq, 32), jnp.float32)
        kv_len = jnp.asarray(77, jnp.int32)
        out = decode_attention(
            q, kq, vq, kv_len, k_scale=ksc, v_scale=vsc, interpret=True
        )
        ref = _xla(q, kq, vq, kv_len, k_scale=ksc, v_scale=vsc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("max_len", [40, 200])  # 40 < one tile; 200 % 64 != 0
    def test_unaligned_max_len_tail_block(self, max_len, monkeypatch):
        """Cache lengths that don't divide the KV tile must mask the padded
        tail block, not read garbage into the softmax (bf16/f32 OOB lanes
        can be anything, including NaN)."""
        monkeypatch.setattr(da, "BLOCK_K", 64)
        k, v = _case(max_len=max_len)
        kq, ksc = _quantize_kv(k)
        vq, vsc = _quantize_kv(v)
        q = _rand(jax.random.PRNGKey(9), (2, 1, 4, 32), jnp.float32)
        kv_len = jnp.asarray(max_len - 3, jnp.int32)
        out = decode_attention(q, k, v, kv_len, interpret=True)
        ref = _xla(q, k, v, kv_len)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
        out = decode_attention(q, kq, vq, kv_len, k_scale=ksc, v_scale=vsc, interpret=True)
        ref = _xla(q, kq, vq, kv_len, k_scale=ksc, v_scale=vsc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_short_kv_len_multi_block(self, monkeypatch):
        """kv_len far below max_len: the dead KV blocks must contribute
        nothing (their DMA is clamped and compute skipped) — parity plus
        invariance to garbage in the dead region."""
        monkeypatch.setattr(da, "BLOCK_K", 32)
        k, v = _case(max_len=128)
        q = _rand(jax.random.PRNGKey(10), (2, 1, 4, 32), jnp.float32)
        kv_len = jnp.asarray(40, jnp.int32)
        ref = _xla(q, k, v, kv_len)
        # poison the dead region with large stale garbage (the cache
        # contract: dead slots hold zeros/stale finite writes): the output
        # must be INVARIANT, proving the masked blocks contribute nothing
        k2 = k.at[:, 40:].set(1e4)
        v2 = v.at[:, 40:].set(-1e4)
        out = decode_attention(q, k2, v2, kv_len, interpret=True)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("sq", [1, 8])
    def test_ragged_matches_xla(self, sq):
        """Right-padded ragged mask (prompt prefix + generated tail) — the
        kernel's scalar-driven mask vs the XLA valid-map construction."""
        k, v = _case(max_len=96)
        q = _rand(jax.random.PRNGKey(11), (2, sq, 4, 32), jnp.float32)
        lens = jnp.asarray([13, 48], jnp.int32)
        kv_len = jnp.asarray(70, jnp.int32)  # width 50, generated [50, 70)
        out = decode_attention(
            q, k, v, kv_len, prompt_lengths=lens, prompt_width=50, interpret=True
        )
        ref = _xla(q, k, v, kv_len, prompt_lengths=lens, prompt_width=50)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_q_block_is_causal(self):
        """At q_len 8, row j must ignore keys written after its own slot:
        poisoning slot kv_len-1 must not change row 0's output."""
        k, v = _case(max_len=64)
        q = _rand(jax.random.PRNGKey(12), (2, 8, 4, 32), jnp.float32)
        kv_len = jnp.asarray(40, jnp.int32)
        out = decode_attention(q, k, v, kv_len, interpret=True)
        k2 = k.at[:, 39].set(1e3)
        v2 = v.at[:, 39].set(1e3)
        out2 = decode_attention(q, k2, v2, kv_len, interpret=True)
        # last row sees slot 39; first row (slot 32) must not
        assert not np.allclose(np.asarray(out[:, 7]), np.asarray(out2[:, 7]))
        np.testing.assert_allclose(
            np.asarray(out[:, 0]), np.asarray(out2[:, 0]), rtol=1e-6, atol=1e-6
        )


class TestRaggedQVerify:
    """The q_len>1 VECTOR-POS path (ISSUE 11, the speculative verify's
    attention): per-row ``q_starts`` ragged query blocks — previously the
    multi-q clamp was uniform (every row's block ends at kv_len-1) and
    had no serving-context coverage."""

    @pytest.mark.parametrize("sq", [2, 3, 5, 8])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_ragged_positions_match_xla(self, sq, dtype):
        """Per-slot cursors at different depths (the serving batch): the
        kernel's prefetched q_starts mask vs the XLA per-row clamp."""
        b = 3
        k, v = _case(b=b, max_len=96, dtype=dtype)
        q = _rand(jax.random.PRNGKey(21), (b, sq, 4, 32), dtype)
        starts = jnp.asarray([5, 61, 30], jnp.int32)  # ragged slot cursors
        kv_len = jnp.max(starts) + sq
        kw = dict(
            prompt_lengths=jnp.zeros(b, jnp.int32), prompt_width=0,
            q_starts=starts,
        )
        out = decode_attention(q, k, v, kv_len, interpret=True, **kw)
        ref = _xla(q, k, v, kv_len, **kw)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), **_tol(dtype)
        )

    @pytest.mark.parametrize("sq", [2, 8])
    def test_uniform_q_starts_equal_default(self, sq):
        """q_starts = kv_len - sq broadcast IS the uniform clamp: both
        kernel and XLA must reproduce their default-path outputs, so the
        ragged mode is a strict generalization, not a fork."""
        b = 2
        k, v = _case(b=b, max_len=96)
        q = _rand(jax.random.PRNGKey(22), (b, sq, 4, 32), jnp.float32)
        kv_len = jnp.asarray(57, jnp.int32)
        starts = jnp.full((b,), 57 - sq, jnp.int32)
        base_kw = dict(prompt_lengths=jnp.zeros(b, jnp.int32), prompt_width=0)
        for fn in (
            lambda **kw: decode_attention(q, k, v, kv_len, interpret=True, **kw),
            lambda **kw: _xla(q, k, v, kv_len, **kw),
        ):
            default = fn(**base_kw)
            ragged = fn(q_starts=starts, **base_kw)
            np.testing.assert_allclose(
                np.asarray(ragged), np.asarray(default), rtol=1e-6, atol=1e-6
            )

    @pytest.mark.parametrize("sq", [2, 6])
    def test_int8_kv_ragged_positions(self, sq):
        """int8-KV deferred dequant composes with the ragged-q mask."""
        b = 3
        k, v = _case(b=b, max_len=96)
        kq, ksc = _quantize_kv(k)
        vq, vsc = _quantize_kv(v)
        q = _rand(jax.random.PRNGKey(23), (b, sq, 4, 32), jnp.float32)
        starts = jnp.asarray([12, 40, 3], jnp.int32)
        kv_len = jnp.max(starts) + sq
        kw = dict(
            prompt_lengths=jnp.zeros(b, jnp.int32), prompt_width=0,
            q_starts=starts, k_scale=ksc, v_scale=vsc,
        )
        out = decode_attention(q, kq, vq, kv_len, interpret=True, **kw)
        ref = _xla(q, kq, vq, kv_len, **kw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_row_isolation_staggered_reuse(self):
        """A deep slot's history must not leak into a shallow slot's
        ragged-q output (staggered slot reuse: slot 1 is a fresh tenant at
        cursor 4 while slot 0 sits at 60): poisoning keys above the
        shallow row's window changes nothing for it."""
        b, sq = 2, 3
        k, v = _case(b=b, max_len=96)
        q = _rand(jax.random.PRNGKey(24), (b, sq, 4, 32), jnp.float32)
        starts = jnp.asarray([60, 4], jnp.int32)
        kv_len = jnp.max(starts) + sq
        kw = dict(
            prompt_lengths=jnp.zeros(b, jnp.int32), prompt_width=0,
            q_starts=starts,
        )
        out = decode_attention(q, k, v, kv_len, interpret=True, **kw)
        # poison row 1's slots ABOVE its query window [0, 4+j] — stale
        # rows a previous deeper tenant left behind
        k2 = k.at[1, 10:].set(1e3)
        v2 = v.at[1, 10:].set(1e3)
        out2 = decode_attention(q, k2, v2, kv_len, interpret=True, **kw)
        np.testing.assert_allclose(
            np.asarray(out[1]), np.asarray(out2[1]), rtol=1e-6, atol=1e-6
        )
        # and the XLA path agrees on the same invariant
        ref2 = _xla(q, k2, v2, kv_len, **kw)
        np.testing.assert_allclose(
            np.asarray(out2), np.asarray(ref2), rtol=2e-5, atol=2e-5
        )

    def test_bad_q_starts_shape_rejected(self):
        k, v = _case()
        q = _rand(jax.random.PRNGKey(25), (2, 2, 4, 32), jnp.float32)
        with pytest.raises(ValueError, match="q_starts"):
            decode_attention(
                q, k, v, jnp.asarray(8, jnp.int32),
                q_starts=jnp.zeros(5, jnp.int32), interpret=True,
            )


class TestDispatch:
    def test_auto_stays_xla_off_tpu(self):
        """On the CPU mesh the auto dispatcher must not route into the
        kernel (interpret mode is a test vehicle, not a serving path)."""
        q = jnp.ones((1, 1, 2, 128), jnp.float32)
        kv = jnp.ones((1, 16, 2, 128), jnp.float32)
        assert not decode_supported(q, kv)

    def test_env_escape_hatch_forces_kernel(self, monkeypatch):
        """NEXUS_DECODE_KERNEL=pallas must route cached_attention into the
        kernel even off-TPU (interpret) — and match the default XLA path."""
        k, v = _case()
        q = _rand(jax.random.PRNGKey(13), (2, 1, 4, 32), jnp.float32)
        kv_len = jnp.asarray(30, jnp.int32)
        ref = cached_attention(q, k, v, kv_len)  # auto -> XLA on CPU
        calls = []
        real = da.decode_attention

        def spy(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(da, "decode_attention", spy)
        monkeypatch.setenv("NEXUS_DECODE_KERNEL", "pallas")
        out = cached_attention(q, k, v, kv_len)
        assert calls, "env escape hatch did not reach the kernel"
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_env_escape_hatch_forces_xla(self, monkeypatch):
        def boom(*a, **kw):  # pragma: no cover - must not be reached
            raise AssertionError("xla escape hatch leaked into the kernel")

        monkeypatch.setattr(da, "decode_attention", boom)
        monkeypatch.setenv("NEXUS_DECODE_KERNEL", "xla")
        k, v = _case()
        q = _rand(jax.random.PRNGKey(14), (2, 1, 4, 32), jnp.float32)
        out = cached_attention(q, k, v, jnp.asarray(30, jnp.int32))  # impl defaults to auto
        assert out.shape == q.shape

    def test_explicit_impl_beats_env(self, monkeypatch):
        """An explicit non-auto impl pins the path: ambient env must not
        re-route it (bench kernel-on/off labeling depends on this)."""
        calls = []
        real = da.decode_attention

        def spy(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(da, "decode_attention", spy)
        monkeypatch.setenv("NEXUS_DECODE_KERNEL", "xla")
        k, v = _case()
        q = _rand(jax.random.PRNGKey(15), (2, 1, 4, 32), jnp.float32)
        cached_attention(q, k, v, jnp.asarray(30, jnp.int32), impl="pallas")
        assert calls, "explicit impl='pallas' was overridden by the env var"

    def test_bad_impl_rejected(self):
        k, v = _case()
        q = jnp.ones((2, 1, 4, 32), jnp.float32)
        with pytest.raises(ValueError, match="decode impl"):
            cached_attention(q, k, v, jnp.asarray(4, jnp.int32), impl="mosaic")

    def test_mixed_scales_rejected(self):
        k, v = _case()
        kq, ksc = _quantize_kv(k)
        q = jnp.ones((2, 1, 4, 32), jnp.float32)
        with pytest.raises(ValueError, match="BOTH"):
            decode_attention(q, kq, v, jnp.asarray(4, jnp.int32), k_scale=ksc, interpret=True)


class TestGenerateEndToEnd:
    """The full jitted decode loop with the kernel forced on (interpret):
    greedy tokens must be IDENTICAL to the XLA path — same model, same
    cache, only the attention implementation differs."""

    @pytest.mark.parametrize("kv_quant", ["", "int8"])
    def test_generate_tokens_match_xla(self, kv_quant):
        import dataclasses
        import functools

        from tpu_nexus.models import LlamaConfig
        from tpu_nexus.models.generate import generate
        from tpu_nexus.models.llama import llama_init

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        run = lambda impl: jax.jit(
            functools.partial(
                generate, cfg=cfg, max_new_tokens=6, kv_quant=kv_quant,
                decode_kernel=impl,
            )
        )(params, prompt)
        np.testing.assert_array_equal(np.asarray(run("pallas")), np.asarray(run("xla")))

    def test_moe_generate_tokens_match_xla(self):
        """The MoE family rides the same cached_attention dispatch — the
        kernel must be family-agnostic."""
        import dataclasses
        import functools

        from tpu_nexus.models import MoeConfig
        from tpu_nexus.models.generate import generate
        from tpu_nexus.models.moe import moe_init

        cfg = dataclasses.replace(
            MoeConfig.tiny(vocab_size=64), capacity_factor=4.0, dtype=jnp.float32
        )
        params = moe_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        run = lambda impl: jax.jit(
            functools.partial(generate, cfg=cfg, max_new_tokens=4, decode_kernel=impl)
        )(params, prompt)
        np.testing.assert_array_equal(np.asarray(run("pallas")), np.asarray(run("xla")))

    def test_scan_layer_loop_reaches_kernel(self):
        """decode_kernel flows through the lax.scan layer path (deep-model
        fallback) exactly as through the unrolled default."""
        import dataclasses

        from tpu_nexus.models import LlamaConfig
        from tpu_nexus.models.generate import decode_step, prefill
        from tpu_nexus.models.llama import llama_init

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        cache, logits = prefill(params, tokens, cfg, max_len=16, kv_quant="int8")
        nxt = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
        pos = jnp.asarray(8, jnp.int32)
        outs = {}
        for unroll in (True, False):
            l_pl, _ = decode_step(
                params, cache, nxt, pos, cfg, unroll_layers=unroll, decode_kernel="pallas"
            )
            l_xla, _ = decode_step(
                params, cache, nxt, pos, cfg, unroll_layers=unroll, decode_kernel="xla"
            )
            np.testing.assert_allclose(
                np.asarray(l_pl), np.asarray(l_xla), rtol=2e-4, atol=2e-4,
                err_msg=f"unroll_layers={unroll}",
            )
            outs[unroll] = l_pl
        np.testing.assert_allclose(
            np.asarray(outs[True]), np.asarray(outs[False]), rtol=1e-5, atol=1e-5
        )

    def test_ragged_generate_matches_xla(self):
        import dataclasses
        import functools

        from tpu_nexus.models import LlamaConfig
        from tpu_nexus.models.generate import generate
        from tpu_nexus.models.llama import llama_init

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        padded = jnp.concatenate(
            [
                jnp.pad(
                    jax.random.randint(jax.random.PRNGKey(5), (1, 5), 0, 64), ((0, 0), (0, 3))
                ),
                jax.random.randint(jax.random.PRNGKey(6), (1, 8), 0, 64),
            ],
            axis=0,
        )
        lengths = jnp.asarray([5, 8], jnp.int32)
        run = lambda impl: jax.jit(
            functools.partial(
                generate, cfg=cfg, max_new_tokens=4,
                prompt_lengths=lengths, decode_kernel=impl,
            )
        )(params, padded)
        np.testing.assert_array_equal(np.asarray(run("pallas")), np.asarray(run("xla")))
