"""Tests for the checkpoint ledger (SURVEY §2.3 pkg/checkpoint contract,
§2.5 schema)."""

from datetime import datetime, timezone

import pytest

from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore, SqliteCheckpointStore


def make_cp(**overrides):
    defaults = dict(
        algorithm="test-algorithm",
        id="f47ac10b-58cc-4372-a567-0e02b2c3d479",
        lifecycle_stage=LifecycleStage.BUFFERED,
        payload_uri="http://localhost/payload",
        received_by_host="host123",
        received_at=datetime(2023, 10, 1, 12, 0, tzinfo=timezone.utc),
        tag="tag_123",
        api_version="v1.0",
    )
    defaults.update(overrides)
    return CheckpointedRequest(**defaults)


def test_is_finished_terminal_stages():
    for stage in (
        LifecycleStage.COMPLETED,
        LifecycleStage.FAILED,
        LifecycleStage.SCHEDULING_FAILED,
        LifecycleStage.DEADLINE_EXCEEDED,
        LifecycleStage.CANCELLED,
    ):
        assert make_cp(lifecycle_stage=stage).is_finished(), stage
    for stage in (
        LifecycleStage.NEW,
        LifecycleStage.BUFFERED,
        LifecycleStage.RUNNING,
        LifecycleStage.PREEMPTED,
    ):
        assert not make_cp(lifecycle_stage=stage).is_finished(), stage


def test_transition_partial_order():
    # terminal absorbs (multi-host first-writer-wins, SURVEY §7.4)
    assert not LifecycleStage.can_transition(LifecycleStage.CANCELLED, LifecycleStage.RUNNING)
    assert not LifecycleStage.can_transition(LifecycleStage.FAILED, LifecycleStage.COMPLETED)
    # monotone forward
    assert LifecycleStage.can_transition(LifecycleStage.BUFFERED, LifecycleStage.RUNNING)
    assert LifecycleStage.can_transition(LifecycleStage.RUNNING, LifecycleStage.FAILED)
    # preempted runs return to RUNNING when the JobSet restarts them
    assert LifecycleStage.can_transition(LifecycleStage.PREEMPTED, LifecycleStage.RUNNING)
    assert LifecycleStage.can_transition(LifecycleStage.RUNNING, LifecycleStage.PREEMPTED)
    # but never regress to pre-run stages
    assert not LifecycleStage.can_transition(LifecycleStage.RUNNING, LifecycleStage.BUFFERED)


def test_deep_copy_isolation():
    cp = make_cp(per_chip_steps={"host0/chip0": 10})
    dup = cp.deep_copy()
    dup.lifecycle_stage = LifecycleStage.FAILED
    dup.per_chip_steps["host0/chip0"] = 99
    assert cp.lifecycle_stage == LifecycleStage.BUFFERED
    assert cp.per_chip_steps["host0/chip0"] == 10


def test_row_round_trip():
    cp = make_cp(
        per_chip_steps={"host0/chip0": 123, "host1/chip3": 456},
        hlo_trace_ref="gs://traces/run1.hlo",
        restart_count=2,
    )
    back = CheckpointedRequest.from_row(cp.to_row())
    assert back == cp


@pytest.fixture(params=["memory", "sqlite"])
def store(request, tmp_path):
    if request.param == "memory":
        yield InMemoryCheckpointStore()
    else:
        s = SqliteCheckpointStore(str(tmp_path / "ledger.db"))
        yield s
        s.close()


def test_store_read_miss_returns_none(store):
    assert store.read_checkpoint("nope", "missing") is None


def test_store_upsert_read_update(store):
    cp = make_cp()
    store.upsert_checkpoint(cp)
    got = store.read_checkpoint(cp.algorithm, cp.id)
    assert got == cp
    # read-modify-write through a deep copy (reference mutation discipline)
    mutated = got.deep_copy()
    mutated.lifecycle_stage = LifecycleStage.FAILED
    mutated.algorithm_failure_cause = "Algorithm encountered a fatal error during execution."
    store.upsert_checkpoint(mutated)
    again = store.read_checkpoint(cp.algorithm, cp.id)
    assert again.lifecycle_stage == LifecycleStage.FAILED
    # the original object must be unaffected (store copies on write)
    assert cp.lifecycle_stage == LifecycleStage.BUFFERED


def test_store_secondary_queries(store):
    store.upsert_checkpoint(make_cp(id="a", lifecycle_stage=LifecycleStage.RUNNING, tag="t1"))
    store.upsert_checkpoint(make_cp(id="b", lifecycle_stage=LifecycleStage.RUNNING, tag="t2"))
    store.upsert_checkpoint(make_cp(id="c", lifecycle_stage=LifecycleStage.CANCELLED, tag="t1"))
    assert {cp.id for cp in store.query_by_stage(LifecycleStage.RUNNING)} == {"a", "b"}
    assert {cp.id for cp in store.query_by_tag("t1")} == {"a", "c"}
    assert {cp.id for cp in store.query_by_host("host123")} == {"a", "b", "c"}


def test_sqlite_lazy_construction(tmp_path):
    # constructing against an unwritable path must not fail until first query
    s = SqliteCheckpointStore("/nonexistent-dir/ledger.db")
    with pytest.raises(Exception):
        s.read_checkpoint("a", "b")


def test_sqlite_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "ledger.db")
    s1 = SqliteCheckpointStore(path)
    s1.upsert_checkpoint(make_cp(per_chip_steps={"h0/c0": 7}))
    s1.close()
    s2 = SqliteCheckpointStore(path)
    got = s2.read_checkpoint("test-algorithm", "f47ac10b-58cc-4372-a567-0e02b2c3d479")
    assert got is not None and got.per_chip_steps == {"h0/c0": 7}
    s2.close()


def test_compare_and_set_applies_only_on_match(store):
    cp = make_cp(lifecycle_stage=LifecycleStage.RUNNING, restart_count=1)
    store.upsert_checkpoint(cp)
    key = (cp.algorithm, cp.id)

    # mismatched expectation: nothing written
    assert not store.compare_and_set(
        *key,
        {"lifecycle_stage": LifecycleStage.BUFFERED},
        {"lifecycle_stage": LifecycleStage.FAILED},
    )
    assert store.read_checkpoint(*key).lifecycle_stage == LifecycleStage.RUNNING

    # matched (multi-column) expectation: applied
    assert store.compare_and_set(
        *key,
        {"lifecycle_stage": LifecycleStage.RUNNING, "restart_count": 1},
        {"lifecycle_stage": LifecycleStage.PREEMPTED, "restart_count": 2,
         "preempted_generation": "gen-uid-7"},
    )
    got = store.read_checkpoint(*key)
    assert got.lifecycle_stage == LifecycleStage.PREEMPTED
    assert got.restart_count == 2
    assert got.preempted_generation == "gen-uid-7"

    # missing row: False, no write
    assert not store.compare_and_set(
        "no-such-alg", "no-such-id", {"lifecycle_stage": "X"}, {"lifecycle_stage": "Y"}
    )

    # the loser of a CAS race observes the winner's value, not its own
    assert not store.compare_and_set(
        *key,
        {"restart_count": 1},
        {"restart_count": 99},
    )
    assert store.read_checkpoint(*key).restart_count == 2


def test_compare_and_set_rejects_unknown_and_merge_only_columns(store):
    cp = make_cp()
    store.upsert_checkpoint(cp)
    with pytest.raises(ValueError):
        store.compare_and_set(cp.algorithm, cp.id, {"nope": 1}, {"tag": "x"})
    with pytest.raises(ValueError):
        store.compare_and_set(cp.algorithm, cp.id, {"tag": "x"}, {"per_chip_steps": {}})


def test_compare_and_set_rejects_empty_fields(store):
    """ADVICE r4: backends used to disagree on the empty-fields edge (CQL/
    sqlite said True without touching the row; base/in-memory verified row
    existence).  The contract is now uniform: empty fields is a caller bug."""
    cp = make_cp()
    store.upsert_checkpoint(cp)
    with pytest.raises(ValueError):
        store.compare_and_set(cp.algorithm, cp.id, {"lifecycle_stage": cp.lifecycle_stage}, {})
    with pytest.raises(ValueError):
        store.compare_and_set(cp.algorithm, cp.id, {}, {})


def test_max_restarts_round_trip(store):
    """The launch-time restart budget is nullable: None (plain-Job runs, or
    pre-upgrade rows) must survive the round trip distinct from 0."""
    budgeted = make_cp(id="budgeted", max_restarts=3)
    unbudgeted = make_cp(id="unbudgeted")
    zero = make_cp(id="zero", max_restarts=0)
    for cp in (budgeted, unbudgeted, zero):
        store.upsert_checkpoint(cp)
    assert store.read_checkpoint(budgeted.algorithm, "budgeted").max_restarts == 3
    assert store.read_checkpoint(budgeted.algorithm, "unbudgeted").max_restarts is None
    assert store.read_checkpoint(budgeted.algorithm, "zero").max_restarts == 0


def test_sqlite_migrates_pre_upgrade_ledger(tmp_path):
    """ADVICE r4 (medium): CREATE TABLE IF NOT EXISTS keeps an existing
    ledger.db's old column set while the upgraded store SELECTs/INSERTs the
    full current set — every query used to error until a manual ALTER.  The
    store now ALTERs missing extension columns in on open."""
    import sqlite3

    from tpu_nexus.checkpoint.store import _COLUMNS

    path = str(tmp_path / "old-ledger.db")
    old_columns = [
        c for c in _COLUMNS if c not in ("preempted_generation", "max_restarts")
    ]
    conn = sqlite3.connect(path)
    cols = ", ".join(
        f"{c} INTEGER" if c == "restart_count" else f"{c} TEXT" for c in old_columns
    )
    conn.execute(f"CREATE TABLE checkpoints ({cols}, PRIMARY KEY (algorithm, id))")
    conn.execute(
        "INSERT INTO checkpoints (algorithm, id, lifecycle_stage, restart_count) "
        "VALUES ('alg', 'old-row', 'RUNNING', 1)"
    )
    conn.commit()
    conn.close()

    store = SqliteCheckpointStore(path)
    # reads of the pre-upgrade row work, with upgrade columns defaulted
    cp = store.read_checkpoint("alg", "old-row")
    assert cp.lifecycle_stage == LifecycleStage.RUNNING
    assert cp.preempted_generation == "" and cp.max_restarts is None
    # writes of the full current column set work too
    cp = cp.deep_copy()
    cp.max_restarts = 3
    cp.preempted_generation = "gen-1"
    store.upsert_checkpoint(cp)
    got = store.read_checkpoint("alg", "old-row")
    assert got.max_restarts == 3 and got.preempted_generation == "gen-1"
    store.close()
