"""Self-healing training chaos (ISSUE 10): in-jit numerical sentinel,
rollback-and-skip recovery, and the step-hang watchdog.

The drills assert the graded-recovery ladder end to end:

* a NaN batch never lands an update (the jit gates params on the sentinel
  verdict), the run rolls back to the newest VERIFIED pre-window
  checkpoint, skips the poisoned draw window on the data cursor, and ends
  COMPLETED with the cause + window in the ledger details — with a
  post-recovery loss **bit-identical** to a fault-free run on the same
  skipped-window schedule;
* a loss spike skips its update in-jit inside a bounded budget; a streak
  past the budget escalates to the same rollback path; recurrence at the
  same window is terminal with a cause ``classify_tpu_failure`` maps to
  the new taxonomy decisions;
* a wedged step (``step-hang``) exits within the watchdog deadline with an
  emergency save and a classified FAILED ledger row — never a silent wedge.

Model is the mnist MLP throughout (tiny jit, float batches — the data
poison modes need float leaves); multi-seed recovery fuzz rides behind the
``slow`` marker.
"""

import json
import os
import subprocess
import sys
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore, SqliteCheckpointStore
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.core.telemetry import RecordingMetrics
from tpu_nexus.models.registry import get_adapter
from tpu_nexus.parallel import MeshSpec
from tpu_nexus.parallel.distributed import ProcessContext
from tpu_nexus.supervisor.taxonomy import DecisionAction, classify_tpu_failure
from tpu_nexus.workload import durability, health
from tpu_nexus.workload.data import DataCursor
from tpu_nexus.workload.faults import (
    FaultPlan,
    PoisonedDataStream,
    maybe_inject,
    wrap_data_stream,
)
from tpu_nexus.workload.harness import WorkloadConfig, run_workload
from tpu_nexus.workload.health import (
    Anomaly,
    HealthConfig,
    HealthMonitor,
    HealthPolicy,
    StepWatchdog,
)
from tpu_nexus.workload.tensor_checkpoint import CURSOR_SIDECAR, TensorCheckpointer

ALGORITHM = "mnist-train"
CTX = ProcessContext(
    run_id="run-health", algorithm=ALGORITHM, process_id=0, num_processes=1, coordinator=None
)


def mnist_cfg(**over):
    base = dict(
        model=get_adapter("mnist"),
        mesh=MeshSpec(fsdp=-1),
        batch_size=8,
        seq_len=16,
        steps=8,
        heartbeat_every=2,
        checkpoint_every=2,
        # warmup 2: the drills poison early draws, and the default warmup
        # of 5 applied steps would let an early spike through un-armed
        health=HealthConfig(warmup_steps=2),
    )
    base.update(over)
    return WorkloadConfig(**base)


def seeded_store(rid=CTX.run_id, algorithm=ALGORITHM):
    store = InMemoryCheckpointStore()
    store.upsert_checkpoint(
        CheckpointedRequest(algorithm=algorithm, id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
    )
    return store


def mnist_stream(seed=0, batch=8):
    return get_adapter("mnist").data(batch, 16, seed=seed)


# -- in-jit sentinel units -----------------------------------------------------


class TestSentinel:
    def _update(self, h, loss, grad, **over):
        kwargs = dict(ema_beta=0.9, spike_factor=4.0, warmup_steps=2)
        kwargs.update(over)
        return health.sentinel_update(
            h, jnp.float32(loss), jnp.float32(grad), **kwargs
        )

    def test_clean_step_applies_and_seeds_ema(self):
        h, flags = self._update(health.health_init(), 2.0, 1.0)
        assert float(flags["health_applied"]) == 1.0
        assert float(flags["health_nonfinite"]) == 0.0
        assert float(h["ema_loss"]) == 2.0 and float(h["ema_grad"]) == 1.0
        assert int(h["count"]) == 1

    def test_nonfinite_flags_and_freezes_ema(self):
        h0 = health.health_init()
        h0, _ = self._update(h0, 2.0, 1.0)
        h1, flags = self._update(h0, float("nan"), 1.0)
        assert float(flags["health_nonfinite"]) == 1.0
        assert float(flags["health_applied"]) == 0.0
        assert float(h1["ema_loss"]) == float(h0["ema_loss"])
        assert int(h1["count"]) == int(h0["count"])  # warmup clock frozen too
        _, flags_inf = self._update(h0, 2.0, float("inf"))
        assert float(flags_inf["health_nonfinite"]) == 1.0

    def test_spike_skips_after_warmup_only(self):
        h = health.health_init()
        for _ in range(2):
            h, _ = self._update(h, 2.0, 1.0)
        # armed: 4x the EMA trips, and the spike must not drag the EMA up
        h2, flags = self._update(h, 9.0, 1.0)
        assert float(flags["health_spike"]) == 1.0
        assert float(flags["health_applied"]) == 0.0
        assert float(h2["ema_loss"]) == pytest.approx(float(h["ema_loss"]))
        # not armed: the same ratio during warmup applies
        cold, _ = self._update(health.health_init(), 2.0, 1.0)
        _, flags_cold = self._update(cold, 9.0, 1.0, warmup_steps=5)
        assert float(flags_cold["health_spike"]) == 0.0
        assert float(flags_cold["health_applied"]) == 1.0

    def test_grad_spike_detected_independently(self):
        h = health.health_init()
        for _ in range(2):
            h, _ = self._update(h, 2.0, 1.0)
        _, flags = self._update(h, 2.0, 40.0)
        assert float(flags["health_spike"]) == 1.0

    def test_negative_loss_baseline_never_spikes(self):
        """A factor-over-baseline threshold is meaningless over a negative
        EMA (log-likelihood losses): every finite step must still apply —
        NaN/Inf protection and the grad-norm spike remain the guards."""
        h = health.health_init()
        for _ in range(3):
            h, flags = self._update(h, -5.0, 1.0)
            assert float(flags["health_applied"]) == 1.0
        # warm, baseline negative: a much "worse" (higher) loss still applies
        _, flags = self._update(h, -0.1, 1.0)
        assert float(flags["health_spike"]) == 0.0
        assert float(flags["health_applied"]) == 1.0
        # grad spike still armed on the nonnegative grad baseline
        _, flags = self._update(h, -5.0, 40.0)
        assert float(flags["health_spike"]) == 1.0
        # NaN still caught
        _, flags = self._update(h, float("nan"), 1.0)
        assert float(flags["health_nonfinite"]) == 1.0

    def test_gated_train_step_freezes_params_on_nan(self):
        """The in-jit gate: a NaN batch's update never lands, bit-for-bit,
        while the step counter (data-cursor clock) still advances."""
        from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, build_mesh
        from tpu_nexus.workload.train import TrainConfig, init_train_state, make_train_step

        adapter = get_adapter("mnist")
        mesh = build_mesh(MeshSpec(fsdp=-1))
        tcfg = TrainConfig(warmup_steps=2, total_steps=50)
        state = init_train_state(jax.random.PRNGKey(0), adapter, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        step_fn = make_train_step(
            adapter, tcfg, mesh, LOGICAL_RULES_FSDP_TP, health=HealthConfig(warmup_steps=2)
        )
        data = mnist_stream()
        with mesh:
            state, _ = step_fn(state, jax.tree.map(jnp.asarray, next(data)))
            before = jax.tree.map(np.asarray, state["params"])
            bad = next(data)
            bad = {"x": np.full_like(bad["x"], np.nan), "y": bad["y"]}
            state, m = step_fn(state, jax.tree.map(jnp.asarray, bad))
        assert float(m["health_nonfinite"]) == 1.0
        after = jax.tree.map(np.asarray, state["params"])
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_array_equal(a, b)
        assert int(state["step"]) == 2


# -- host-side monitor / policy / config units ---------------------------------


def flags(nonfinite=0.0, spike=0.0, applied=1.0, loss=2.0, grad=1.0):
    return {
        "health_nonfinite": np.float32(nonfinite),
        "health_spike": np.float32(spike),
        "health_applied": np.float32(applied),
        "loss": np.float32(loss),
        "grad_norm": np.float32(grad),
    }


class TestMonitorAndPolicy:
    def test_readback_is_one_step_delayed(self):
        mon = HealthMonitor(HealthConfig())
        assert mon.push(0, flags(nonfinite=1.0, applied=0.0)) is None  # stored, not read
        anomaly = mon.push(1, flags())
        assert anomaly is not None and anomaly.kind == "numeric-nan"
        assert anomaly.step == 0
        assert "loss=" in anomaly.detail

    def test_drain_flushes_the_final_step(self):
        mon = HealthMonitor(HealthConfig())
        assert mon.push(5, flags(nonfinite=1.0, applied=0.0)) is None
        anomaly = mon.drain()
        assert anomaly is not None and anomaly.step == 5
        assert mon.drain() is None  # cleared

    def test_spike_streak_escalates_past_budget(self):
        rec = RecordingMetrics()
        mon = HealthMonitor(HealthConfig(skip_budget=2), metrics=rec)
        mon.push(0, flags(spike=1.0, applied=0.0))
        assert mon.push(1, flags(spike=1.0, applied=0.0)) is None  # streak 1
        assert mon.push(2, flags(spike=1.0, applied=0.0)) is None  # streak 2
        anomaly = mon.push(3, flags(spike=1.0, applied=0.0))      # streak 3 > 2
        assert anomaly is not None and anomaly.kind == "loss-spike"
        assert anomaly.step == 0  # the window START, not the breach step
        assert rec.tagged_counts[("train.skip", ("cause:loss-spike",))] == 3

    def test_applied_step_resets_the_streak(self):
        mon = HealthMonitor(HealthConfig(skip_budget=2))
        for i in range(2):
            mon.push(i, flags(spike=1.0, applied=0.0))
        mon.push(2, flags())  # healthy step — classify(1) keeps streak at 2
        assert mon.push(3, flags(spike=1.0, applied=0.0)) is None  # classify(2): reset
        assert mon.push(4, flags(spike=1.0, applied=0.0)) is None  # streak 1
        assert mon.push(5, flags()) is None                        # streak 2
        assert mon.drain() is None  # classify(5): healthy, streak reset again
        assert mon.skips_observed == 4

    def test_sentinel_less_metrics_ignored(self):
        mon = HealthMonitor(HealthConfig())
        assert mon.push(0, {"loss": np.float32(1.0)}) is None
        assert mon.drain() is None

    def test_policy_grades(self):
        policy = HealthPolicy(HealthConfig(max_rollbacks=2))
        nan = Anomaly("numeric-nan", 5)
        verdict, why = policy.decide(nan, None)
        assert verdict == "fail" and "no verified checkpoint" in why
        verdict, _ = policy.decide(nan, 4)
        assert verdict == "rollback"
        policy.record({"restored_step": 4, "flagged_step": 5})
        # same target, flagged at/before the previous window: recurrence
        verdict, why = policy.decide(nan, 4)
        assert verdict == "fail" and "recurred" in why
        # same target but a LATER flagged step: fresh poison arriving
        # before the next commit boundary — healable, not recurrence
        verdict, _ = policy.decide(Anomaly("numeric-nan", 8), 4)
        assert verdict == "rollback"
        policy.record({"restored_step": 4, "flagged_step": 8})
        verdict, why = policy.decide(Anomaly("numeric-nan", 12), 2)
        assert verdict == "fail" and "budget" in why

    def test_config_validation_and_env(self):
        with pytest.raises(ValueError, match="ema_beta"):
            HealthConfig(ema_beta=1.0)
        with pytest.raises(ValueError, match="spike_factor"):
            HealthConfig(spike_factor=1.0)
        with pytest.raises(ValueError, match="step_timeout_s"):
            HealthConfig(step_timeout_s=-1)
        cfg = HealthConfig.from_env(
            {
                "NEXUS_HEALTH": "0",
                "NEXUS_HEALTH_SPIKE_FACTOR": "6.5",
                "NEXUS_STEP_TIMEOUT_S": "12",
            }
        )
        assert cfg.enabled is False
        assert cfg.spike_factor == 6.5 and cfg.step_timeout_s == 12.0
        assert HealthConfig.from_env({}).enabled is True

    def test_classified_failure_texts_map_to_taxonomy(self):
        nan_text = health.classified_failure_text(
            Anomaly("numeric-nan", 3, "loss=nan"), "no verified checkpoint"
        )
        spike_text = health.classified_failure_text(
            Anomaly("loss-spike", 7, "streak of 4"), "recurred after a rollback"
        )
        assert classify_tpu_failure(nan_text) == DecisionAction.TO_FAIL_NUMERIC_NAN
        assert classify_tpu_failure(spike_text) == DecisionAction.TO_FAIL_LOSS_SPIKE
        assert classify_tpu_failure(health.hang_cause(5, 2.0)) == (
            DecisionAction.TO_FAIL_STEP_HANG
        )


# -- step-hang watchdog units --------------------------------------------------


class TestStepWatchdog:
    def test_fires_after_deadline(self):
        fired = []
        dog = StepWatchdog(0.05, lambda step, t: fired.append((step, t)), poll_s=0.01)
        dog.start()
        dog.arm(7)
        deadline = time.monotonic() + 2.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.01)
        dog.stop()
        assert fired == [(7, 0.05)] and dog.fired

    def test_disarm_prevents_firing(self):
        fired = []
        dog = StepWatchdog(0.05, lambda step, t: fired.append(step), poll_s=0.01)
        dog.start()
        dog.arm(1)
        dog.disarm()
        time.sleep(0.2)
        dog.stop()
        assert fired == [] and not dog.fired

    def test_rearming_extends_the_deadline(self):
        fired = []
        dog = StepWatchdog(0.08, lambda step, t: fired.append(step), poll_s=0.01)
        dog.start()
        for step in range(4):  # steady progress: each arm resets the clock
            dog.arm(step)
            time.sleep(0.03)
        dog.disarm()
        time.sleep(0.15)
        dog.stop()
        assert fired == []

    def test_rejects_nonpositive_timeout(self):
        with pytest.raises(ValueError, match="timeout_s"):
            StepWatchdog(0.0, lambda step, t: None)


# -- data cursor ---------------------------------------------------------------


def counting_stream():
    i = 0
    while True:
        yield i
        i += 1


class TestDataCursor:
    def test_draws_and_position(self):
        cur = DataCursor(counting_stream())
        assert [next(cur) for _ in range(3)] == [0, 1, 2]
        assert cur.position == 3

    def test_pending_window_is_skipped_at_its_start(self):
        cur = DataCursor(counting_stream(), skips=[[2, 5]])
        assert [next(cur) for _ in range(4)] == [0, 1, 5, 6]
        assert cur.position == 7  # skipped draws count

    def test_abutting_windows(self):
        cur = DataCursor(counting_stream(), skips=[[1, 2], [2, 4]])
        assert [next(cur) for _ in range(2)] == [0, 4]

    def test_recorded_past_window_draws_nothing(self):
        cur = DataCursor(counting_stream())
        for _ in range(5):
            next(cur)
        cur.skip_window(2, 5)  # bookkeeping of draws that already happened
        assert next(cur) == 5
        assert cur.state()["skips"] == [[2, 5]]

    def test_state_roundtrip_reproduces_schedule(self):
        cur = DataCursor(counting_stream(), skips=[[3, 6]])
        consumed = [next(cur) for _ in range(5)]
        restored = DataCursor.restore(counting_stream(), cur.state())
        assert [next(restored) for _ in range(3)] == [next(cur) for _ in range(3)]
        assert consumed == [0, 1, 2, 6, 7]

    def test_rejects_bad_windows_and_rewind(self):
        cur = DataCursor(counting_stream())
        with pytest.raises(ValueError, match="invalid skip window"):
            cur.skip_window(4, 4)
        next(cur)
        with pytest.raises(ValueError, match="rewind"):
            cur.fast_forward(0)


# -- fault plumbing ------------------------------------------------------------


class TestFaultPlumbing:
    def test_poisoned_stream_nan(self):
        stream = PoisonedDataStream(mnist_stream(), "nan-grads", at_draw=1, times=2)
        clean = next(stream)
        assert np.isfinite(clean["x"]).all()
        for _ in range(2):
            bad = next(stream)
            assert np.isnan(bad["x"]).all()
            assert bad["y"].dtype.kind == "i"  # int leaves untouched
        assert np.isfinite(next(stream)["x"]).all()
        assert stream.fired["count"] == 2

    def test_poisoned_stream_spike_scales(self):
        stream = PoisonedDataStream(mnist_stream(), "loss-spike", at_draw=0)
        bad = next(stream)
        assert np.isfinite(bad["x"]).all()
        assert np.abs(bad["x"]).max() > 1e3

    def test_int_only_batch_refused(self):
        def ints():
            while True:
                yield np.zeros((2, 4), np.int32)

        stream = PoisonedDataStream(ints(), "nan-grads", at_draw=0)
        with pytest.raises(ValueError, match="no float leaves"):
            next(stream)

    def test_wrap_passthrough_for_other_modes(self):
        data = mnist_stream()
        assert wrap_data_stream(FaultPlan(mode="hbm-oom", step=0), data) is data
        assert wrap_data_stream(FaultPlan(mode=None, step=0), data) is data

    def test_maybe_inject_guards_vacuous_drills(self):
        with pytest.raises(ValueError, match="no wrapped data stream"):
            maybe_inject(FaultPlan(mode="nan-grads", step=3), 3)
        maybe_inject(FaultPlan(mode="nan-grads", step=3), 3, data_faults_handled=True)
        with pytest.raises(ValueError, match="no armed step-hang watchdog"):
            maybe_inject(FaultPlan(mode="step-hang", step=3), 3)
        # off-step: silent either way
        maybe_inject(FaultPlan(mode="step-hang", step=3), 2)

    def test_vacuous_data_drill_fails_loudly(self, monkeypatch):
        """A poison draw index the run never reaches must raise, not exit 0
        looking like a passed drill."""
        monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
        monkeypatch.setenv("NEXUS_FAULT_STEP", "99")
        with pytest.raises(RuntimeError, match="injected nothing"):
            run_workload(
                mnist_cfg(checkpoint_every=0), store=seeded_store(), ctx=CTX,
                lifecycle=LifecycleContext(),
            )


# -- the recovery drills -------------------------------------------------------


def _comparator_loss(skips, steps, seed=0):
    """Fault-free run on the skipped-window schedule: the same config (and
    the same init/data seed), data pre-skipping exactly the windows the
    recovered run skipped."""
    result = run_workload(
        mnist_cfg(steps=steps, checkpoint_every=0, seed=seed),
        store=None,
        ctx=ProcessContext(
            run_id=str(uuid.uuid4()), algorithm=ALGORITHM, process_id=0,
            num_processes=1, coordinator=None,
        ),
        data=DataCursor(mnist_stream(seed=seed), skips=skips),
        lifecycle=LifecycleContext(),
    )
    return result["loss"]


def test_nan_grads_rollback_and_skip_bit_identical(tmp_path, monkeypatch):
    """The flagship drill: a NaN batch at draw 5 → the in-jit gate discards
    the update, the harness rolls back to verified step 4 (checkpoint 6 is
    abandoned: it postdates the window), the cursor skips draws [4, 7), and
    the run COMPLETES with a loss bit-identical to a fault-free run on the
    same post-skip schedule."""
    d = str(tmp_path)
    store = seeded_store()
    rec = RecordingMetrics()
    monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "5")
    result = run_workload(
        mnist_cfg(checkpoint_dir=d), store=store, ctx=CTX,
        lifecycle=LifecycleContext(), telemetry=rec,
    )
    monkeypatch.delenv("NEXUS_FAULT_MODE")
    monkeypatch.delenv("NEXUS_FAULT_STEP")
    assert result["final_step"] == 8
    [event] = result["health_rollbacks"]
    assert event["cause"] == "numeric-nan"
    assert event["flagged_step"] == 5
    assert event["restored_step"] == 4
    window = event["skipped_window"]
    assert window[0] == 4 and window[1] >= 6  # the poisoned draw 5 is inside
    assert window[0] <= 5 < window[1]
    # metrics: anomaly + rollback counted with the cause tag
    assert rec.tagged_counts[("train.anomaly", ("cause:numeric-nan",))] == 1
    assert rec.tagged_counts[("train.rollback", ("cause:numeric-nan",))] == 1
    # ledger: COMPLETED, details carry cause + window, pointer verifies
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.COMPLETED
    details = json.loads(row.algorithm_failure_details)
    assert details["health_rollback"][0]["cause"] == "numeric-nan"
    assert details["health_rollback"][0]["skipped_window"] == window
    assert row.tensor_checkpoint_uri == f"{d}/8"
    tc = TensorCheckpointer(d)
    assert tc.latest_verified_step() == 8
    tc.close()
    # checkpoint 6 was healthy but on the abandoned trajectory
    assert any(n.startswith("6" + durability.ABANDONED_SUFFIX) for n in os.listdir(d))
    # THE acceptance bar: bit-identical to the fault-free post-skip schedule
    assert result["loss"] == _comparator_loss([window], steps=8)


@pytest.mark.parametrize("seed", [1, 2])
def test_nan_recovery_multi_seed(tmp_path, monkeypatch, seed):
    """Recovery determinism is not a seed-0 accident."""
    store = seeded_store()
    monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "5")
    result = run_workload(
        mnist_cfg(checkpoint_dir=str(tmp_path), seed=seed), store=store, ctx=CTX,
        lifecycle=LifecycleContext(),
    )
    monkeypatch.delenv("NEXUS_FAULT_MODE")
    monkeypatch.delenv("NEXUS_FAULT_STEP")
    assert store.read_checkpoint(ALGORITHM, CTX.run_id).lifecycle_stage == (
        LifecycleStage.COMPLETED
    )
    [event] = result["health_rollbacks"]
    assert result["loss"] == _comparator_loss([event["skipped_window"]], steps=8, seed=seed)


def test_restart_after_recovery_reproduces_schedule(tmp_path, monkeypatch):
    """The cursor sidecar end to end: a RESTARTED run resumes the recovered
    run's checkpoint AND its skipped-window schedule (a bare step-count
    fast-forward would re-consume the skipped draws and fork the
    trajectory) — final loss bit-identical to a fault-free run that
    pre-skipped the window."""
    d = str(tmp_path)
    monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "5")
    first = run_workload(
        mnist_cfg(checkpoint_dir=d), store=seeded_store(), ctx=CTX,
        lifecycle=LifecycleContext(),
    )
    monkeypatch.delenv("NEXUS_FAULT_MODE")
    monkeypatch.delenv("NEXUS_FAULT_STEP")
    [event] = first["health_rollbacks"]
    resumed = run_workload(
        mnist_cfg(steps=12, checkpoint_dir=d),
        store=None,
        ctx=ProcessContext(
            run_id=str(uuid.uuid4()), algorithm=ALGORITHM, process_id=0,
            num_processes=1, coordinator=None,
        ),
        lifecycle=LifecycleContext(),
    )
    assert resumed["resumed_from"] == 8 and resumed["final_step"] == 12
    assert resumed["loss"] == _comparator_loss([event["skipped_window"]], steps=12)


def test_nan_recurrence_is_terminal_and_classified(tmp_path, monkeypatch):
    """Poison every draw from 3 on: the first anomaly rolls back and skips;
    the data is still poisoned after the window, so the second anomaly
    resolves to the SAME restore step — terminal, with a cause the
    supervisor classifies as NUMERIC_NAN."""
    store = seeded_store()
    monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "3")
    monkeypatch.setenv("NEXUS_FAULT_TIMES", "50")
    rec = RecordingMetrics()
    with pytest.raises(RuntimeError, match="cannot self-heal") as ei:
        run_workload(
            mnist_cfg(steps=10, checkpoint_dir=str(tmp_path)), store=store, ctx=CTX,
            lifecycle=LifecycleContext(), telemetry=rec,
        )
    assert classify_tpu_failure(str(ei.value)) == DecisionAction.TO_FAIL_NUMERIC_NAN
    assert rec.tagged_counts[("train.rollback", ("cause:numeric-nan",))] == 1
    assert rec.tagged_counts[("train.anomaly", ("cause:numeric-nan",))] == 2
    # the crash path stays honest: RUNNING (supervisor's call) + trace ref
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.RUNNING
    assert row.hlo_trace_ref.startswith("file://")


def test_nan_without_checkpointer_fails_classified(monkeypatch):
    """No durability configured → nothing to roll back to → classified
    terminal failure instead of burning the deadline on garbage."""
    monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "2")
    with pytest.raises(RuntimeError, match="no verified checkpoint") as ei:
        run_workload(
            mnist_cfg(checkpoint_every=0), store=seeded_store(), ctx=CTX,
            lifecycle=LifecycleContext(),
        )
    assert classify_tpu_failure(str(ei.value)) == DecisionAction.TO_FAIL_NUMERIC_NAN


def test_loss_spike_skips_within_budget(monkeypatch):
    """A single spiking batch costs one skipped update and NOTHING else:
    no rollback, run completes, skip visible in metrics."""
    store = seeded_store()
    rec = RecordingMetrics()
    monkeypatch.setenv("NEXUS_FAULT_MODE", "loss-spike")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "5")
    result = run_workload(
        mnist_cfg(steps=10, checkpoint_every=0), store=store, ctx=CTX,
        lifecycle=LifecycleContext(), telemetry=rec,
    )
    assert result["final_step"] == 10
    assert result["health_skips"] == 1
    assert "health_rollbacks" not in result
    assert rec.tagged_counts[("train.skip", ("cause:loss-spike",))] == 1
    assert np.isfinite(result["loss"])
    assert store.read_checkpoint(ALGORITHM, CTX.run_id).lifecycle_stage == (
        LifecycleStage.COMPLETED
    )


def test_loss_spike_ladder_rollback_then_terminal(tmp_path, monkeypatch):
    """The full spike ladder: every batch from draw 4 on spikes → the skip
    budget exhausts → rollback-and-skip → the poison persists → recurrence
    at the same window → terminal, classified LOSS_SPIKE."""
    store = seeded_store()
    rec = RecordingMetrics()
    monkeypatch.setenv("NEXUS_FAULT_MODE", "loss-spike")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "4")
    monkeypatch.setenv("NEXUS_FAULT_TIMES", "50")
    with pytest.raises(RuntimeError, match="cannot self-heal") as ei:
        run_workload(
            mnist_cfg(steps=16, checkpoint_dir=str(tmp_path)), store=store, ctx=CTX,
            lifecycle=LifecycleContext(), telemetry=rec,
        )
    assert classify_tpu_failure(str(ei.value)) == DecisionAction.TO_FAIL_LOSS_SPIKE
    assert rec.tagged_counts[("train.rollback", ("cause:loss-spike",))] == 1
    assert rec.counters["train.skip"] >= 4  # the budget's worth of skips, twice


def test_cursor_sidecar_is_manifested(tmp_path):
    """The cursor sidecar is covered by the commit manifest: present in
    every committed step, and tampering with it fails verification exactly
    like a tampered tensor."""
    d = str(tmp_path)
    run_workload(
        mnist_cfg(steps=4, checkpoint_dir=d), store=seeded_store(), ctx=CTX,
        lifecycle=LifecycleContext(),
    )
    sidecar = os.path.join(d, "4", CURSOR_SIDECAR)
    assert os.path.isfile(sidecar)
    assert json.load(open(sidecar))["position"] == 4
    durability.verify_step(os.path.join(d, "4"), 4)
    with open(sidecar, "a", encoding="utf-8") as fh:
        fh.write(" ")
    with pytest.raises(durability.CheckpointCorrupt):
        durability.verify_step(os.path.join(d, "4"), 4)


def test_health_disabled_restores_seed_behavior(monkeypatch):
    """NEXUS_HEALTH=0 escape hatch: no sentinel metrics, no monitor, a NaN
    batch trains through exactly as before this layer existed."""
    monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "2")
    result = run_workload(
        mnist_cfg(steps=6, checkpoint_every=0, health=HealthConfig(enabled=False)),
        store=seeded_store(), ctx=CTX, lifecycle=LifecycleContext(),
    )
    assert result["final_step"] == 6
    assert "health_rollbacks" not in result and "health_skips" not in result
    assert "health_nonfinite" not in result


def test_hang_handler_saves_cursor_and_merges_evidence(tmp_path, monkeypatch):
    """The hang handler's emergency save carries the cursor sidecar (a
    restart after a hang must replay any health-skipped window) and its
    FAILED details re-merge the run's earlier rollback evidence instead of
    overwriting the column."""
    from tpu_nexus.workload.harness import LedgerReporter, _make_hang_handler

    d = str(tmp_path)
    tc = TensorCheckpointer(d)
    state = {"params": {"w": jnp.arange(4.0)}, "step": jnp.int32(5)}
    cursor = DataCursor(iter([]))
    cursor.position = 8
    cursor.skip_window(4, 7)
    store = seeded_store()
    rec = RecordingMetrics()
    exited = []
    monkeypatch.setattr(os, "_exit", lambda code: exited.append(code))
    handler = _make_hang_handler(
        mnist_cfg(), tc, LedgerReporter(store, CTX), CTX, rec,
        {"snap": (state, cursor.state())},
        evidence=lambda: {"health_rollback": [{"cause": "numeric-nan"}]},
    )
    handler(6, 2.0)
    assert exited == [health.STEP_HANG_EXIT_CODE]
    # emergency step committed WITH the cursor sidecar, and it verifies
    assert tc.latest_verified_step() == 5
    assert tc.load_cursor(5) == {"position": 8, "skips": [[4, 7]]}
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.FAILED
    details = json.loads(row.algorithm_failure_details)
    assert details["hang_step"] == 6 and details["emergency_step"] == 5
    assert details["health_rollback"] == [{"cause": "numeric-nan"}]
    assert rec.tagged_counts[("train.anomaly", ("cause:step-hang",))] == 1
    tc.close()


def test_hang_handler_exit_survives_reporter_failure(tmp_path, monkeypatch):
    """The exit is exception-safe: a ledger write blowing up mid-protocol
    (locked sqlite, dead session) must not leave the wedged process alive
    — os._exit runs in a finally."""
    from tpu_nexus.workload.harness import LedgerReporter, _make_hang_handler

    class ExplodingReporter(LedgerReporter):
        def failed(self, cause, details=""):
            raise RuntimeError("database is locked")

    exited = []
    monkeypatch.setattr(os, "_exit", lambda code: exited.append(code))
    handler = _make_hang_handler(
        mnist_cfg(), None, ExplodingReporter(seeded_store(), CTX), CTX,
        RecordingMetrics(), {},
    )
    with pytest.raises(RuntimeError, match="database is locked"):
        handler(4, 1.0)  # patched _exit returns, so the raise surfaces here
    assert exited == [health.STEP_HANG_EXIT_CODE]


def test_pre_health_checkpoint_restores_with_reseeded_sentinel(tmp_path):
    """Upgrade migration: a checkpoint written BEFORE the health subtree
    existed must still resume (structure-mismatch fallback reseeds the
    sentinel state) — an image upgrade must not crash every durable run
    mid-flight."""
    from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, build_mesh
    from tpu_nexus.workload.train import TrainConfig, init_train_state

    d = str(tmp_path)
    adapter = get_adapter("mnist")
    mesh = build_mesh(MeshSpec(fsdp=-1))
    state = init_train_state(
        jax.random.PRNGKey(0), adapter, TrainConfig(), mesh, LOGICAL_RULES_FSDP_TP
    )
    legacy = {k: v for k, v in state.items() if k != "health"}
    legacy["step"] = jnp.int32(4)
    tc = TensorCheckpointer(d)
    tc.save(4, legacy)
    tc.commit(4)
    tc.close()
    result = run_workload(
        mnist_cfg(steps=8, checkpoint_dir=d), store=seeded_store(), ctx=CTX,
        lifecycle=LifecycleContext(),
    )
    assert result["resumed_from"] == 4 and result["final_step"] == 8
    assert np.isfinite(result["loss"])


def test_second_poison_window_heals_with_second_rollback(tmp_path):
    """Fresh poison landing AFTER a recovery but BEFORE the next commit
    boundary resolves to the same restore target — that is a NEW window
    (flagged later than the previous one), healable by a second
    rollback-and-skip, not a terminal recurrence."""

    def nan_at(draws, seed=0):
        src = mnist_stream(seed=seed)
        i = 0
        while True:
            batch = next(src)
            if i in draws:
                batch = {"x": np.full_like(batch["x"], np.nan), "y": batch["y"]}
            i += 1
            yield batch

    d = str(tmp_path)
    store = seeded_store()
    result = run_workload(
        mnist_cfg(steps=10, checkpoint_every=4, checkpoint_dir=d),
        store=store, ctx=CTX, data=nan_at({5, 9}), lifecycle=LifecycleContext(),
    )
    events = result["health_rollbacks"]
    assert [e["restored_step"] for e in events] == [4, 4]
    assert events[0]["flagged_step"] < events[1]["flagged_step"]
    assert store.read_checkpoint(ALGORITHM, CTX.run_id).lifecycle_stage == (
        LifecycleStage.COMPLETED
    )
    # the second window subsumes the first: a fault-free run skipping just
    # the final window reproduces the recovered trajectory bit-for-bit
    assert result["loss"] == _comparator_loss([events[1]["skipped_window"]], steps=10)


def test_mid_run_quarantine_during_recovery_is_reported(tmp_path, monkeypatch):
    """A checkpoint that rots AFTER the startup scan and is quarantined by
    the recovery's before-scan must land in the corruption evidence
    (summary, ledger details, train.ckpt_rollback metric) — not vanish
    into ckpt.rollbacks unreported."""
    from tpu_nexus.workload.faults import flip_committed_leaf

    d = str(tmp_path)
    store = seeded_store()
    rec = RecordingMetrics()

    def rotting_stream():
        src = mnist_stream()
        i = 0
        while True:
            batch = next(src)
            if i == 5:
                # silent rot lands on the newest committed step right as
                # the poison batch goes out: the recovery's before-scan
                # (limit 6 -> candidates 2,4) must quarantine 4 and fall
                # back to 2, and REPORT the quarantine
                flip_committed_leaf(os.path.join(d, "4"))
                batch = {"x": np.full_like(batch["x"], np.nan), "y": batch["y"]}
            i += 1
            yield batch

    result = run_workload(
        mnist_cfg(checkpoint_dir=d), store=store, ctx=CTX,
        data=rotting_stream(), lifecycle=LifecycleContext(), telemetry=rec,
    )
    [event] = result["health_rollbacks"]
    assert event["restored_step"] == 2  # rolled past the rotten 4
    assert [e["step"] for e in result["ckpt_rollbacks"]] == [4]
    assert [e["cause"] for e in result["ckpt_rollbacks"]] == ["corrupt"]
    assert rec.tagged_counts[("train.ckpt_rollback", ("cause:corrupt",))] == 1
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.COMPLETED
    details = json.loads(row.algorithm_failure_details)
    assert details["ckpt_rollback"][0]["step"] == 4
    assert any(n.startswith("4" + durability.QUARANTINE_SUFFIX) for n in os.listdir(d))


# -- step-hang watchdog drill (subprocess: the watchdog os._exit()s) -----------

_HANG_SCRIPT = """
import sys
from tpu_nexus.parallel.smap import force_virtual_cpu_devices
force_virtual_cpu_devices(8)
from tpu_nexus.checkpoint.store import SqliteCheckpointStore
from tpu_nexus.models.registry import get_adapter
from tpu_nexus.parallel import MeshSpec
from tpu_nexus.parallel.distributed import ProcessContext
from tpu_nexus.workload.harness import WorkloadConfig, run_workload
from tpu_nexus.workload.health import HealthConfig

ledger, ckpt_dir, rid, algo = sys.argv[1:5]
run_workload(
    WorkloadConfig(
        model=get_adapter("mnist"), mesh=MeshSpec(fsdp=-1), batch_size=8,
        seq_len=16, steps=8, heartbeat_every=2, checkpoint_every=2,
        checkpoint_dir=ckpt_dir,
        health=HealthConfig(warmup_steps=2, step_timeout_s=2.0),
    ),
    store=SqliteCheckpointStore(ledger),
    ctx=ProcessContext(run_id=rid, algorithm=algo, process_id=0,
                       num_processes=1, coordinator=None),
)
"""


def test_step_hang_watchdog_drill(tmp_path):
    """The acceptance drill: a wedged step (sleep-forever at step 3) exits
    within the watchdog deadline with exit code 70, a FAILED ledger row
    whose cause classifies as STEP_HANG, and an emergency save of the last
    completed step — never a silent wedge until the k8s deadline."""
    rid = str(uuid.uuid4())
    ledger = str(tmp_path / "ledger.db")
    store = SqliteCheckpointStore(ledger)
    store.upsert_checkpoint(
        CheckpointedRequest(algorithm=ALGORITHM, id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
    )
    env = dict(
        os.environ, NEXUS_FAULT_MODE="step-hang", NEXUS_FAULT_STEP="3",
    )
    t0 = time.monotonic()
    proc = subprocess.run(
        [
            sys.executable, "-c", _HANG_SCRIPT,
            ledger, str(tmp_path / "ckpt"), rid, ALGORITHM,
        ],
        capture_output=True, text=True, timeout=240, env=env,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == health.STEP_HANG_EXIT_CODE, (
        proc.returncode, proc.stderr[-2000:],
    )
    # the whole subprocess (jax import + 3 steps + 2s deadline + save)
    # stays far inside the k8s-deadline regime a silent wedge would burn
    assert elapsed < 200, elapsed
    row = store.read_checkpoint(ALGORITHM, rid)
    assert row.lifecycle_stage == LifecycleStage.FAILED
    assert row.algorithm_failure_cause.startswith("step-hang")
    assert classify_tpu_failure(row.algorithm_failure_cause) == (
        DecisionAction.TO_FAIL_STEP_HANG
    )
    details = json.loads(row.algorithm_failure_details)
    assert details["hang_step"] == 3 and details["deadline_s"] == 2.0
    # emergency save: the last COMPLETED step (3) committed and verifies,
    # and the ledger pointer was published behind the barrier
    assert details["emergency_step"] == 3
    assert row.tensor_checkpoint_uri == f"{tmp_path / 'ckpt'}/3"
    tc = TensorCheckpointer(str(tmp_path / "ckpt"))
    assert tc.latest_verified_step() == 3
    tc.close()
    store.close()


# -- slow tier: multi-seed recovery fuzz ---------------------------------------


@pytest.mark.slow
def test_recovery_fuzz_seed_matrix(tmp_path, monkeypatch):
    """Multi-seed, multi-draw fuzz of the rollback-and-skip invariant: for
    every (seed, poisoned draw) the run COMPLETES and the post-recovery
    loss is bit-identical to the fault-free run on the recovered run's own
    skipped-window schedule."""
    for seed in range(5):
        for draw in (3, 5, 6):
            d = str(tmp_path / f"s{seed}-d{draw}")
            monkeypatch.setenv("NEXUS_FAULT_MODE", "nan-grads")
            monkeypatch.setenv("NEXUS_FAULT_STEP", str(draw))
            store = seeded_store()
            result = run_workload(
                mnist_cfg(checkpoint_dir=d, seed=seed), store=store, ctx=CTX,
                lifecycle=LifecycleContext(),
            )
            monkeypatch.delenv("NEXUS_FAULT_MODE")
            monkeypatch.delenv("NEXUS_FAULT_STEP")
            assert store.read_checkpoint(ALGORITHM, CTX.run_id).lifecycle_stage == (
                LifecycleStage.COMPLETED
            ), (seed, draw)
            [event] = result["health_rollbacks"]
            assert event["skipped_window"][0] <= draw < event["skipped_window"][1]
            assert result["loss"] == _comparator_loss(
                [event["skipped_window"]], steps=8, seed=seed
            ), (seed, draw)
