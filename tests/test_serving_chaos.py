"""Serving chaos harness (ISSUE 4): fault-isolation invariants under
injected step faults, deadlines, backpressure, and SIGTERM drain.

Layers, cheapest first:

* targeted fault scenarios against the deterministic FakeExecutor — each
  recovery path (transient retry, per-request FAILED retirement, prefill
  fault, deadline eviction, shed, drain) exercised in isolation;
* a seeded randomized chaos fuzz: random traffic × random fault plans,
  asserting after EVERY step that slot accounting is consistent, and at
  the end that every submitted request reached a terminal state, no slot
  leaked, unaffected requests' outputs are identical to the fault-free
  run of the same schedule, and every failure cause was recorded
  (quick tier ≤25 seeds for tier-1; the full matrix is ``slow``);
* real-model fault parity: a ModelExecutor decode with an injected HBM
  OOM — the surviving requests' greedy tokens must equal one-shot
  ``generate`` (the fault must not corrupt the cache of the batch);
* the ledger acceptance: SIGTERM / cancelled lifecycle mid-serve lands an
  honest PREEMPTED row with per-cause retirement counts — not a hang,
  not a stack trace.
"""

import json
import os
import random
import signal

import numpy as np
import pytest

from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.parallel.distributed import ProcessContext
from tpu_nexus.serving import (
    FifoScheduler,
    QueueFull,
    Request,
    RequestState,
    SchedulerConfig,
    ServingEngine,
    ServingMetrics,
    StepFaultPolicy,
)
from tpu_nexus.serving.engine import (
    CAUSE_DEADLINE,
    CAUSE_DRAIN_GRACE,
    CAUSE_DRAIN_SHED,
)
from tpu_nexus.workload.faults import (
    EXECUTOR_FAULT_MODES,
    FaultPlan,
    FaultyExecutor,
    maybe_inject,
    wrap_executor,
)

from tests.test_serving_engine import FakeExecutor


class StepClock:
    """Deterministic engine clock: 1.0 'seconds' per engine step, so
    deadlines and grace budgets are expressed in steps and the fuzz never
    touches the wall clock."""

    def __init__(self) -> None:
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float = 1.0) -> None:
        self.now += dt


def make_engine(num_slots=2, max_len=64, executor=None, sched_cfg=None, clock=None):
    executor = executor or FakeExecutor(num_slots, max_len)
    policy = StepFaultPolicy(sleep=lambda s: None, rng=random.Random(0))
    return ServingEngine(
        executor,
        scheduler=FifoScheduler(sched_cfg or SchedulerConfig()),
        metrics=ServingMetrics(),
        fault_policy=policy,
        clock=clock or StepClock(),
    )


def drive(eng, clock=None, max_steps=2000):
    while eng.has_work:
        assert eng.steps < max_steps, "engine did not drain"
        eng.step()
        eng.slots.verify_consistent()
        if clock is not None:
            clock.advance()


# -- targeted fault scenarios ---------------------------------------------------


class TestStepFaultRecovery:
    def test_hbm_oom_retires_only_the_youngest(self):
        fake = FakeExecutor(3, 64)
        faulty = FaultyExecutor(fake, "step-hbm-oom", at_step=2)
        eng = make_engine(executor=faulty)
        reqs = [eng.submit(np.array([10 * (i + 1)]), 8) for i in range(3)]
        drive(eng)
        states = [r.state for r in reqs]
        assert states.count(RequestState.FAILED) == 1
        victim = next(r for r in reqs if r.state == RequestState.FAILED)
        assert victim is reqs[2]  # youngest admission implicated
        assert victim.cause == "hbm-oom"
        for r in reqs[:2]:
            assert r.state == RequestState.FINISHED
            assert len(r.output_tokens) == 8
        assert eng.metrics.step_faults == {"hbm-oom": 1}
        assert eng.metrics.retired_causes == {"hbm-oom": 1}
        assert eng.slots.free_count == 3  # the victim's slot was released

    def test_prefill_fault_retires_only_that_request(self):
        fake = FakeExecutor(2, 64)
        faulty = FaultyExecutor(fake, "step-hbm-oom", at_begin=1)
        eng = make_engine(executor=faulty)
        a = eng.submit(np.array([5]), 4)
        b = eng.submit(np.array([7]), 4)  # second prefill faults
        c = eng.submit(np.array([9]), 4)
        drive(eng)
        assert a.state == RequestState.FINISHED
        assert b.state == RequestState.FAILED
        assert b.cause == "hbm-oom"
        assert b.output_tokens == []  # never produced a token
        assert c.state == RequestState.FINISHED  # refilled the freed slot
        assert eng.slots.free_count == 2

    def test_transient_ici_heals_within_retry_budget(self):
        fake = FakeExecutor(2, 64)
        faulty = FaultyExecutor(fake, "step-ici", at_step=1, times=2)
        eng = make_engine(executor=faulty)
        reqs = [eng.submit(np.array([3 * (i + 1)]), 6) for i in range(2)]
        drive(eng)
        for r in reqs:
            assert r.state == RequestState.FINISHED
            assert len(r.output_tokens) == 6
        # the fault was absorbed by retries, invisible to every request
        assert eng.metrics.step_faults == {}
        assert eng.metrics.step_retries >= 2
        assert eng.fault_policy.retries_used >= 2

    def test_ici_exhaustion_falls_back_to_retirement(self):
        fake = FakeExecutor(2, 64)
        # more consecutive faults than the whole retry budget can absorb
        faulty = FaultyExecutor(fake, "step-ici", at_step=0, times=10)
        eng = make_engine(executor=faulty)
        reqs = [eng.submit(np.array([4 * (i + 1)]), 6) for i in range(2)]
        drive(eng)
        failed = [r for r in reqs if r.state == RequestState.FAILED]
        assert failed, "exhausted transient retries must retire a victim"
        for r in failed:
            assert r.cause == "ici-link-failure"
        assert eng.metrics.step_faults.get("ici-link-failure", 0) == len(failed)
        assert eng.slots.free_count == 2

    def test_device_state_lost_fails_batch_engine_survives(self):
        """A fault that consumed the executor's device state (TPU cache
        donation) must fail the WHOLE in-flight batch with the classified
        cause — and the engine keeps serving later admissions on the fresh
        cache, instead of unwinding on an 'Array has been deleted' retry."""
        from tpu_nexus.serving import DeviceStateLost
        from tpu_nexus.workload.faults import MSG_ICI

        class StateLosingExecutor(FakeExecutor):
            def __init__(self, num_slots, max_len, lose_at):
                super().__init__(num_slots, max_len)
                self.lose_at = lose_at
                self.step_calls = 0

            def step(self, tokens, cursors):
                call = self.step_calls
                self.step_calls += 1
                if call == self.lose_at:
                    raise DeviceStateLost(RuntimeError(MSG_ICI))
                return super().step(tokens, cursors)

        eng = make_engine(executor=StateLosingExecutor(2, 64, lose_at=2))
        doomed = [eng.submit(np.array([5 * (i + 1)]), 10) for i in range(2)]
        later = eng.submit(np.array([30]), 4)  # queued behind the batch
        drive(eng)
        for r in doomed:
            assert r.state == RequestState.FAILED
            # the ICI wording classified, but retry was rightly skipped
            assert r.cause == "ici-link-failure"
        assert later.state == RequestState.FINISHED
        assert len(later.output_tokens) == 4
        assert eng.slots.free_count == 2
        assert eng.metrics.step_faults == {"ici-link-failure": 1}

    def test_model_executor_escalates_deleted_donated_cache(self):
        """The real executor: a RuntimeError whose aftermath left the
        donated cache deleted raises DeviceStateLost and reinstalls a
        fresh cache (simulating the TPU donation path on CPU by deleting
        the buffers by hand)."""
        import jax

        from tpu_nexus.models import LlamaConfig
        from tpu_nexus.models.llama import llama_init
        from tpu_nexus.serving import DeviceStateLost, ModelExecutor
        from tpu_nexus.workload.faults import MSG_ICI

        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        executor = ModelExecutor(params, cfg, num_slots=1, max_len=8)

        def boom(*a, **k):
            raise RuntimeError(MSG_ICI)

        executor._step = boom
        for leaf in jax.tree.leaves(executor.cache):
            leaf.delete()
        with pytest.raises(DeviceStateLost):
            executor.step(np.zeros(1, np.int32), np.zeros(1, np.int32))
        # fresh cache installed: the engine can keep admitting
        assert not any(
            leaf.is_deleted() for leaf in jax.tree.leaves(executor.cache)
        )
        # with the cache INTACT the original error re-raises for the
        # normal classify/retry path
        with pytest.raises(RuntimeError, match="ICI link"):
            executor.step(np.zeros(1, np.int32), np.zeros(1, np.int32))

    def test_unclassified_runtime_error_propagates(self):
        class BrokenExecutor(FakeExecutor):
            def step(self, tokens, cursors):
                raise RuntimeError("list index out of range")  # an engine BUG

        eng = make_engine(executor=BrokenExecutor(2, 64))
        eng.submit(np.array([1]), 4)
        with pytest.raises(RuntimeError, match="list index"):
            drive(eng)

    def test_backoff_grows_and_is_jittered(self):
        policy = StepFaultPolicy(
            backoff_base_s=0.1, backoff_max_s=1.0, rng=random.Random(7)
        )
        waits = [policy.backoff_s(a) for a in range(6)]
        assert all(0.0 <= w <= 1.0 for w in waits)
        assert max(waits) <= 1.0  # ceiling respected
        # jitter: not all equal, and ceilings grow with attempt
        assert len({round(w, 6) for w in waits}) > 1


class TestDeadlines:
    def test_queued_deadline_evicts_without_device_time(self):
        clock = StepClock()
        eng = make_engine(num_slots=1, clock=clock)
        hog = eng.submit(np.array([1]), 30)
        waiting = eng.submit(np.array([2]), 4, deadline_s=3.0)
        drive(eng, clock=clock)
        assert hog.state == RequestState.FINISHED
        assert waiting.state == RequestState.EVICTED
        assert waiting.cause == CAUSE_DEADLINE
        assert waiting.output_tokens == []
        assert eng.metrics.retired_causes == {CAUSE_DEADLINE: 1}

    def test_decoding_deadline_evicts_partial_output(self):
        clock = StepClock()
        eng = make_engine(num_slots=1, clock=clock)
        req = eng.submit(np.array([1]), 30, deadline_s=5.0)
        drive(eng, clock=clock)
        assert req.state == RequestState.EVICTED
        assert req.cause == CAUSE_DEADLINE
        assert 0 < len(req.output_tokens) < 30  # partial output delivered

    def test_slow_step_trips_deadlines(self):
        clock = StepClock()
        fake = FakeExecutor(1, 64)
        # the injected slowness advances the SAME clock the engine reads
        faulty = FaultyExecutor(
            fake, "slow-step", at_step=0, slow_s=2.0, sleep=clock.advance
        )
        eng = make_engine(executor=faulty, clock=clock)
        req = eng.submit(np.array([1]), 30, deadline_s=6.0)
        drive(eng, clock=clock)
        assert req.state == RequestState.EVICTED
        assert req.cause == CAUSE_DEADLINE
        assert faulty.injected > 0

    def test_deadline_validation(self):
        with pytest.raises(ValueError, match="deadline_s"):
            Request(request_id="r", prompt=np.array([1]), max_new_tokens=1, deadline_s=0)

    def test_cancel_wins_over_deadline_attribution(self):
        """A request that is both cancel-requested and past-deadline when
        the step runs retires CANCELLED (the user's intent) — not as an
        SLO violation an operator would chase."""
        clock = StepClock()
        eng = make_engine(num_slots=1, clock=clock)
        req = eng.submit(np.array([1]), 30, deadline_s=2.0)
        eng.step()
        eng.cancel(req.request_id)
        clock.advance(5.0)  # now past the deadline too
        eng.step()
        assert req.state == RequestState.CANCELLED
        assert req.cause == ""
        assert CAUSE_DEADLINE not in eng.metrics.retired_causes


class TestBackpressure:
    def test_queue_limit_sheds_with_counter(self):
        eng = make_engine(num_slots=1, sched_cfg=SchedulerConfig(max_queue=2))
        eng.submit(np.array([1]), 4)
        eng.step()  # first request takes the slot; queue is now empty
        kept = [eng.submit(np.array([2]), 4), eng.submit(np.array([3]), 4)]
        with pytest.raises(QueueFull, match="queue at capacity"):
            eng.submit(np.array([4]), 4)
        assert eng.metrics.shed_total == 1
        # a shed request leaves NO trace in the engine
        assert len(eng.requests) == 3
        drive(eng)
        for r in kept:
            assert r.state == RequestState.FINISHED

    def test_unbounded_by_default(self):
        eng = make_engine(num_slots=1)
        for i in range(50):
            eng.submit(np.array([i + 1]), 2)
        assert eng.metrics.shed_total == 0


class TickingExecutor(FakeExecutor):
    """FakeExecutor that advances the engine clock one 'second' per decode
    step — so ``drain()``'s INTERNAL loop consumes grace budget (the
    outer-loop clock advance never runs inside drain)."""

    def __init__(self, num_slots, max_len, clock):
        super().__init__(num_slots, max_len)
        self.clock = clock

    def step(self, tokens, cursors):
        self.clock.advance()
        return super().step(tokens, cursors)


class TestDrain:
    def test_drain_finishes_short_evicts_long_sheds_queued(self):
        clock = StepClock()
        eng = make_engine(
            num_slots=2, clock=clock, executor=TickingExecutor(2, 64, clock)
        )
        short = eng.submit(np.array([1]), 3)
        long = eng.submit(np.array([2]), 60)
        queued = eng.submit(np.array([3]), 3)  # no free slot at drain time
        eng.step()  # short+long admitted and decoding
        summary = eng.drain(grace_s=10.0)
        assert short.state == RequestState.FINISHED
        assert long.state == RequestState.EVICTED
        assert long.cause == CAUSE_DRAIN_GRACE
        assert queued.state == RequestState.EVICTED
        assert queued.cause == CAUSE_DRAIN_SHED
        assert summary["drain_shed_queue"] == 1
        assert summary["drain_evicted"] == 1
        assert summary["drain_finished"] == 1
        assert eng.slots.free_count == 2
        assert not eng.has_work
        # admission is over: post-drain submits shed
        with pytest.raises(QueueFull, match="draining"):
            eng.submit(np.array([9]), 2)
        assert eng.metrics.shed_total == 1

    def test_zero_grace_evicts_everything_in_flight(self):
        eng = make_engine(num_slots=2)
        a = eng.submit(np.array([1]), 50)
        b = eng.submit(np.array([2]), 50)
        eng.step()
        eng.drain(grace_s=0.0)
        for r in (a, b):
            assert r.state == RequestState.EVICTED
            assert r.cause == CAUSE_DRAIN_GRACE
        assert eng.metrics.retired_causes == {CAUSE_DRAIN_GRACE: 2}

    def test_drain_steps_keep_deadline_and_finish_semantics(self):
        clock = StepClock()
        eng = make_engine(
            num_slots=2, clock=clock, executor=TickingExecutor(2, 64, clock)
        )
        dl = eng.submit(np.array([1]), 60, deadline_s=4.0)
        ok = eng.submit(np.array([2]), 6)
        eng.step()
        eng.drain(grace_s=50.0)
        assert ok.state == RequestState.FINISHED
        assert dl.state == RequestState.EVICTED
        assert dl.cause == CAUSE_DEADLINE  # deadline beat the grace budget


def test_retirement_cause_tags_reach_telemetry():
    """The cause must survive all the way to the metrics backend as a tag
    dimension, not just the in-process dicts — that is what an operator's
    dashboard groups by (RUNBOOK §10)."""
    from tpu_nexus.core.telemetry import RecordingMetrics

    rec = RecordingMetrics()
    faulty = FaultyExecutor(FakeExecutor(1, 64), "step-hbm-oom", at_step=0)
    eng = ServingEngine(
        faulty,
        metrics=ServingMetrics(rec),
        fault_policy=StepFaultPolicy(sleep=lambda s: None),
        clock=StepClock(),
    )
    eng.submit(np.array([1]), 4)
    drive(eng)
    assert rec.tagged_counts[
        ("serving.requests_retired", ("cause:hbm-oom", "state:failed"))
    ] == 1
    assert rec.tagged_counts[("serving.step_faults", ("cause:hbm-oom",))] == 1


# -- fault-plan env contract ----------------------------------------------------


class TestFaultPlanContract:
    def test_env_parses_serving_fields(self):
        plan = FaultPlan.from_env(
            {
                "NEXUS_FAULT_MODE": "step-ici",
                "NEXUS_FAULT_STEP": "3",
                "NEXUS_FAULT_TIMES": "2",
                "NEXUS_FAULT_SLOW_S": "0.25",
            }
        )
        assert (plan.mode, plan.step, plan.times, plan.slow_s) == ("step-ici", 3, 2, 0.25)
        assert plan.request is None
        wrapped = wrap_executor(plan, FakeExecutor(2, 16))
        assert isinstance(wrapped, FaultyExecutor)
        assert (wrapped.at_step, wrapped.at_begin) == (3, None)

    def test_request_targeting(self):
        plan = FaultPlan.from_env(
            {"NEXUS_FAULT_MODE": "step-hbm-oom", "NEXUS_FAULT_REQUEST": "1"}
        )
        wrapped = wrap_executor(plan, FakeExecutor(2, 16))
        assert (wrapped.at_step, wrapped.at_begin) == (None, 1)

    def test_non_executor_modes_pass_through(self):
        fake = FakeExecutor(2, 16)
        assert wrap_executor(FaultPlan(mode=None, step=0), fake) is fake
        assert wrap_executor(FaultPlan(mode="hbm-oom", step=0), fake) is fake

    def test_maybe_inject_executor_modes_need_a_wrapped_executor(self):
        """The serve-engine loop declares it wrapped its executor and the
        hook stays silent; any OTHER loop reaching the fault step with an
        executor mode must fail loudly — a drill that injects nothing and
        reports success is worse than no drill."""
        for mode in EXECUTOR_FAULT_MODES:
            plan = FaultPlan(mode=mode, step=0)
            maybe_inject(plan, 0, executor_faults_handled=True)  # silent
            maybe_inject(plan, 5, executor_faults_handled=False)  # wrong step
            with pytest.raises(ValueError, match="serving-executor"):
                maybe_inject(plan, 0)  # unwrapped loop at the fault step

    def test_unknown_executor_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown executor fault mode"):
            FaultyExecutor(FakeExecutor(1, 8), "step-meteor")


# -- seeded randomized chaos fuzz ------------------------------------------------


def _build_schedule(rng, max_len):
    """One traffic schedule: (arrival_step, prompt, max_new, deadline)."""
    n_requests = int(rng.integers(2, 14))
    arrivals = sorted(int(a) for a in rng.integers(0, 25, size=n_requests))
    schedule = []
    for a in arrivals:
        prompt_len = int(rng.integers(1, max_len // 2))
        max_new = int(rng.integers(1, max_len - prompt_len + 1))
        prompt = rng.integers(1, 100, size=prompt_len)
        deadline = float(rng.integers(4, 60)) if rng.random() < 0.25 else None
        schedule.append((a, prompt, max_new, deadline))
    return schedule


def _run_schedule(schedule, num_slots, max_len, sched_cfg, fault=None):
    """Drive one schedule to completion; returns (requests, engine)."""
    clock = StepClock()
    executor = FakeExecutor(num_slots, max_len)
    if fault is not None:
        mode, kwargs = fault
        executor = FaultyExecutor(executor, mode, sleep=lambda s: None, **kwargs)
    eng = make_engine(
        num_slots=num_slots, max_len=max_len, executor=executor,
        sched_cfg=sched_cfg, clock=clock,
    )
    requests = []
    step, idx = 0, 0
    while idx < len(schedule) or eng.has_work:
        while idx < len(schedule) and schedule[idx][0] <= step:
            _, prompt, max_new, deadline = schedule[idx]
            try:
                requests.append(
                    eng.submit(prompt, max_new, request_id=f"r{idx}", deadline_s=deadline)
                )
            except QueueFull:
                requests.append(None)  # shed at admission: no lifecycle at all
            idx += 1
        if eng.has_work:
            eng.step()
        # the per-step invariants: allocator consistency + owner/active parity
        eng.slots.verify_consistent()
        owners = eng.slots.owners()
        assert len(set(owners.values())) == len(owners)
        for slot, rid in owners.items():
            assert eng.requests[rid].slot == slot
            assert not eng.requests[rid].is_terminal()
        clock.advance()
        step += 1
        assert step < 3000, "chaos schedule did not drain"
    return requests, eng


def _chaos_one(seed: int) -> None:
    rng = np.random.default_rng(seed)
    num_slots = int(rng.integers(1, 5))
    max_len = int(rng.integers(8, 48))
    sched_cfg = SchedulerConfig(
        prefill_token_budget=int(rng.integers(1, 2 * max_len)),
        evict_after_steps=int(rng.choice([0, 0, 3])),
        max_queue=int(rng.choice([0, 0, 0, 2, 5])),
    )
    schedule = _build_schedule(rng, max_len)
    fault_kind = rng.choice(["none", "step-hbm-oom", "step-ici", "begin-hbm-oom"])
    fault = None
    if fault_kind == "step-hbm-oom":
        fault = ("step-hbm-oom", {"at_step": int(rng.integers(0, 20)),
                                  "times": int(rng.integers(1, 3))})
    elif fault_kind == "step-ici":
        fault = ("step-ici", {"at_step": int(rng.integers(0, 20)),
                              "times": int(rng.integers(1, 8))})
    elif fault_kind == "begin-hbm-oom":
        fault = ("step-hbm-oom", {"at_begin": int(rng.integers(0, 6))})

    # fault-free reference run of the SAME schedule
    ref_requests, _ = _run_schedule(schedule, num_slots, max_len, sched_cfg)
    requests, eng = _run_schedule(schedule, num_slots, max_len, sched_cfg, fault)

    failed_causes = 0
    for req in requests:
        if req is None:
            continue  # shed at admission — deliberately no lifecycle
        # 1. every submitted request reached a terminal state
        assert req.is_terminal(), f"seed {seed}: {req.request_id} in {req.state}"
        if req.state == RequestState.FINISHED:
            assert len(req.output_tokens) == req.max_new_tokens
        elif req.state == RequestState.FAILED:
            # 4. failure causes recorded on request AND metrics
            assert req.cause in ("hbm-oom", "ici-link-failure"), req.cause
            failed_causes += 1
        elif req.state == RequestState.EVICTED:
            assert req.cause, f"seed {seed}: EVICTED without a cause"
    assert failed_causes == sum(eng.metrics.step_faults.values())
    for cause, n in eng.metrics.step_faults.items():
        assert eng.metrics.retired_causes.get(cause, 0) == n

    # 2. no slot leak / double assignment survived to the end
    eng.slots.verify_consistent()
    assert eng.slots.used_count == 0
    assert eng.slots.free_count == num_slots

    # 3. unaffected requests: token streams identical to the fault-free run.
    # The fake executor's tokens are a pure function of the prompt, so ANY
    # divergence means the fault bled across slots (cross-request
    # corruption), which is exactly what fault isolation forbids.
    ref_by_id = {r.request_id: r for r in ref_requests if r is not None}
    for req in requests:
        if req is None or req.state != RequestState.FINISHED:
            continue
        ref = ref_by_id.get(req.request_id)
        if ref is not None and ref.state == RequestState.FINISHED:
            assert req.output_tokens == ref.output_tokens, (
                f"seed {seed}: fault bled into unaffected request {req.request_id}"
            )


def test_chaos_fuzz_quick():
    """Tier-1 slice of the chaos matrix (seeds 0..24, ~seconds)."""
    for seed in range(25):
        _chaos_one(seed)


@pytest.mark.slow
def test_chaos_fuzz_full():
    """The full seed matrix — run with ``-m slow`` (not part of tier-1's
    870 s budget on the 2-CPU CI box)."""
    for seed in range(25, 200):
        _chaos_one(seed)


# -- real-model fault parity -----------------------------------------------------


def test_model_executor_fault_keeps_survivors_token_identical():
    """An HBM-OOM step fault against the REAL jitted executor: the victim
    retires FAILED, and every surviving request's greedy tokens remain
    identical to one-shot ``generate`` — the fault must not corrupt the
    shared cache (ISSUE 4 acceptance)."""
    import jax
    import jax.numpy as jnp

    from tpu_nexus.models import LlamaConfig
    from tpu_nexus.models.generate import generate
    from tpu_nexus.models.llama import llama_init

    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    from tpu_nexus.serving import ModelExecutor

    B, S, T = 3, 8, 6
    rng = np.random.default_rng(13)
    prompts = rng.integers(1, cfg.vocab_size, size=(B, S)).astype(np.int32)
    executor = ModelExecutor(params, cfg, num_slots=B, max_len=S + T)
    faulty = FaultyExecutor(executor, "step-hbm-oom", at_step=2)
    eng = make_engine(executor=faulty)
    reqs = [eng.submit(prompts[i], T, request_id=f"r{i}") for i in range(B)]
    drive(eng)

    assert reqs[2].state == RequestState.FAILED  # youngest implicated
    assert reqs[2].cause == "hbm-oom"
    for i in (0, 1):
        assert reqs[i].state == RequestState.FINISHED
        solo = np.asarray(
            generate(
                params, jnp.asarray(prompts[i : i + 1]), cfg,
                max_new_tokens=T, max_len=S + T,
            )
        )[0]
        np.testing.assert_array_equal(np.asarray(reqs[i].output_tokens), solo)


# -- ledger acceptance: drain lands an honest PREEMPTED --------------------------


CTX = ProcessContext(
    run_id="chaos-1", algorithm="llama-serve", process_id=0, num_processes=1,
    coordinator=None,
)


def _seeded_store():
    store = InMemoryCheckpointStore()
    store.upsert_checkpoint(
        CheckpointedRequest(
            algorithm=CTX.algorithm, id=CTX.run_id,
            lifecycle_stage=LifecycleStage.BUFFERED,
        )
    )
    return store


def _serve_cfg(**overrides):
    from tpu_nexus.models import LlamaConfig
    from tpu_nexus.workload.serve import ServeConfig

    defaults = dict(
        model=LlamaConfig.tiny(), batch_size=2, prompt_len=8,
        gen_tokens=16, rounds=2, heartbeat_every=2, drain_grace_s=0.0,
    )
    defaults.update(overrides)
    return ServeConfig(**defaults)


class TestDrainLedger:
    def test_cancelled_lifecycle_mid_serve_lands_preempted_with_causes(self):
        """Deterministic drain drill without real signals: the lifecycle
        cancels between submission rounds (the injectable seam), so round
        1's requests are in flight when admission stops.  The ledger must
        land PREEMPTED with the per-cause retirement counts in the details
        column, and every request must reach a terminal state."""
        from tpu_nexus.workload.serve import run_serve_engine

        store = _seeded_store()
        lifecycle = LifecycleContext()
        cfg = _serve_cfg()

        def prompts():
            rng = np.random.default_rng(3)
            n = 0
            while True:
                if n == 2:  # warmup batch + round-1 batch delivered
                    lifecycle.cancel(reason="SIGTERM")
                yield rng.integers(1, 64, size=(cfg.batch_size, cfg.prompt_len))
                n += 1

        summary = run_serve_engine(
            cfg, store=store, ctx=CTX, prompts=prompts(), lifecycle=lifecycle
        )
        assert summary["drained"] is True
        row = store.read_checkpoint(CTX.algorithm, CTX.run_id)
        assert row.lifecycle_stage == LifecycleStage.PREEMPTED
        assert "SIGTERM" in row.algorithm_failure_cause
        details = json.loads(row.algorithm_failure_details)
        assert details["retired_causes"], details
        # zero grace: everything in flight was evicted with a drain cause
        drain_causes = {CAUSE_DRAIN_GRACE, CAUSE_DRAIN_SHED}
        assert set(details["retired_causes"]) <= drain_causes
        assert sum(details["retired_causes"].values()) == summary["requests"]
        assert details["drain_evicted"] + details["drain_shed_queue"] >= 1
        # summary mirrors the ledger
        assert summary["retired_causes"] == details["retired_causes"]

    def test_real_sigterm_via_drain_fault_mode(self, monkeypatch):
        """The full drill: NEXUS_FAULT_MODE=drain-sigterm sends a REAL
        SIGTERM mid-loop; the installed handler cancels the lifecycle and
        the drain protocol produces PREEMPTED — no hang, no stack trace."""
        from tpu_nexus.core.signals import setup_signal_context
        from tpu_nexus.workload.serve import run_serve_engine

        monkeypatch.setenv("NEXUS_FAULT_MODE", "drain-sigterm")
        monkeypatch.setenv("NEXUS_FAULT_STEP", "1")
        saved = {s: signal.getsignal(s) for s in (signal.SIGTERM, signal.SIGINT)}
        try:
            lifecycle = setup_signal_context(install=True)
            store = _seeded_store()
            summary = run_serve_engine(
                _serve_cfg(gen_tokens=24), store=store, ctx=CTX, lifecycle=lifecycle
            )
        finally:
            for sig, handler in saved.items():
                signal.signal(sig, handler)
        assert lifecycle.cancelled and lifecycle.reason == "SIGTERM"
        assert summary["drained"] is True
        row = store.read_checkpoint(CTX.algorithm, CTX.run_id)
        assert row.lifecycle_stage == LifecycleStage.PREEMPTED
        assert "SIGTERM" in row.algorithm_failure_cause
        assert json.loads(row.algorithm_failure_details)["retired_causes"]

    def test_completed_run_stays_completed(self):
        """No cancellation → the drain path is never taken and the ledger
        lands COMPLETED exactly as before (regression guard)."""
        from tpu_nexus.workload.serve import run_serve_engine

        store = _seeded_store()
        summary = run_serve_engine(
            _serve_cfg(gen_tokens=4, rounds=1), store=store, ctx=CTX,
            lifecycle=LifecycleContext(),
        )
        assert summary["drained"] is False
        assert summary["finished"] == summary["requests"] == 2
        row = store.read_checkpoint(CTX.algorithm, CTX.run_id)
        assert row.lifecycle_stage == LifecycleStage.COMPLETED


# -- deferred-dispatch chaos (ISSUE 12) ------------------------------------------


class TestDeferredDispatchFaults:
    """Faults under overlapped dispatch surface at the DEFERRED
    materialization — exactly one step late — with the same
    one-fault-one-request contract as the synchronous loop."""

    def _engine(self, executor, decode_steps=1):
        policy = StepFaultPolicy(sleep=lambda s: None, rng=random.Random(0))
        return ServingEngine(
            executor,
            scheduler=FifoScheduler(SchedulerConfig()),
            metrics=ServingMetrics(),
            fault_policy=policy,
            clock=StepClock(),
            overlap=True,
        )

    def _drive(self, eng, max_steps=2000):
        while eng.has_work:
            assert eng.steps < max_steps, "engine did not drain"
            eng.step()
            eng.slots.verify_consistent()
            eng._pipeline.verify_consistent()

    def test_hbm_oom_surfaces_one_step_late_retiring_dispatch_youngest(self):
        fake = FakeExecutor(2, 64)
        faulty = FaultyExecutor(fake, "step-hbm-oom", at_step=1)
        eng = self._engine(faulty)
        a = eng.submit(np.array([10]), 8)
        b = eng.submit(np.array([20]), 8)
        eng.step()  # dispatch #0 rides ahead
        eng.step()  # dispatch #1 faults AT THE CALL — held on the pending
        assert a.state == RequestState.DECODING
        assert b.state == RequestState.DECODING  # nothing surfaced yet
        eng.step()  # the deferred materialization surfaces the fault
        assert b.state == RequestState.FAILED  # dispatch-time youngest
        assert b.cause == "hbm-oom"
        assert a.state != RequestState.FAILED
        self._drive(eng)
        assert a.state == RequestState.FINISHED
        assert a.output_tokens == [11 + i for i in range(8)]  # survivor exact
        assert eng.metrics.step_faults == {"hbm-oom": 1}
        assert eng.metrics.retired_causes == {"hbm-oom": 1}
        assert eng.slots.free_count == 2 and eng._pipeline.depth == 0

    def test_transient_ici_heals_at_materialization(self):
        fake = FakeExecutor(2, 64)
        faulty = FaultyExecutor(fake, "step-ici", at_step=1, times=2)
        eng = self._engine(faulty)
        a = eng.submit(np.array([10]), 6)
        b = eng.submit(np.array([20]), 6)
        self._drive(eng)
        assert a.state == b.state == RequestState.FINISHED
        assert a.output_tokens == [11 + i for i in range(6)]
        assert b.output_tokens == [21 + i for i in range(6)]
        assert eng.metrics.step_faults == {}  # healed, nobody retired
        assert eng.metrics.step_retries >= 1
        assert eng.fault_policy.faults_seen >= 1

    def test_device_state_lost_fails_batch_and_clears_the_pipeline(self):
        from tpu_nexus.serving import DeviceStateLost
        from tpu_nexus.workload.faults import MSG_ICI

        class StateLosingScanExecutor(FakeExecutor):
            def __init__(self, num_slots, max_len, lose_at):
                super().__init__(num_slots, max_len)
                self.lose_at = lose_at
                self.scan_count = 0

            def step_scan(self, *args, **kwargs):
                call = self.scan_count
                self.scan_count += 1
                if call == self.lose_at:
                    raise DeviceStateLost(RuntimeError(MSG_ICI))
                return super().step_scan(*args, **kwargs)

        eng = self._engine(StateLosingScanExecutor(2, 64, lose_at=2))
        doomed = [eng.submit(np.array([5 * (i + 1)]), 10) for i in range(2)]
        later = eng.submit(np.array([30]), 4)  # queued behind the batch
        self._drive(eng)
        for r in doomed:
            assert r.state == RequestState.FAILED
            assert r.cause == "ici-link-failure"
        assert later.state == RequestState.FINISHED
        assert later.output_tokens == [31 + i for i in range(4)]
        assert eng.slots.free_count == 2
        assert eng._pipeline.depth == 0 and eng._pipeline.deferred_slots == 0

    def test_held_device_loss_resolves_before_next_admission(self):
        """A DeviceStateLost captured at dispatch must materialize at the
        TOP of the next step — BEFORE admission — or a request admitted in
        the gap prefills against the silently-reinstalled (zeroed) cache
        and is then wrongly failed by _fail_batch despite the device being
        healthy again (review finding on the phase ordering)."""
        from tpu_nexus.serving import DeviceStateLost
        from tpu_nexus.workload.faults import MSG_ICI

        class StateLosingScanExecutor(FakeExecutor):
            def __init__(self, num_slots, max_len, lose_at):
                super().__init__(num_slots, max_len)
                self.lose_at = lose_at
                self.scan_count = 0

            def step_scan(self, *args, **kwargs):
                call = self.scan_count
                self.scan_count += 1
                if call == self.lose_at:
                    raise DeviceStateLost(RuntimeError(MSG_ICI))
                return super().step_scan(*args, **kwargs)

        eng = self._engine(StateLosingScanExecutor(2, 64, lose_at=1))
        doomed = eng.submit(np.array([10]), 8)
        eng.step()  # dispatch #0 rides ahead
        eng.step()  # dispatch #1 raises DeviceStateLost — HELD
        late = eng.submit(np.array([30]), 4)  # arrives while the fault is held
        eng.step()  # fault resolves FIRST, then admission sees clean state
        assert doomed.state == RequestState.FAILED
        assert doomed.cause == "ici-link-failure"
        assert late.state != RequestState.FAILED  # never caught in the blast
        self._drive(eng)
        assert late.state == RequestState.FINISHED
        assert late.output_tokens == [31 + i for i in range(4)]

    def test_real_model_deferred_fault_survivors_match_generate(self):
        """The HBM-OOM drill against the REAL jitted scan path, overlap +
        decode_steps=2: the implicated request retires one step late, and
        every survivor's greedy tokens stay identical to one-shot
        ``generate`` (the deferred retry must not corrupt the cache)."""
        import jax
        import jax.numpy as jnp

        from tpu_nexus.models import LlamaConfig
        from tpu_nexus.models.generate import generate
        from tpu_nexus.models.llama import llama_init
        from tpu_nexus.serving import ModelExecutor

        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        B, S, T = 3, 8, 6
        rng = np.random.default_rng(13)
        prompts = rng.integers(1, cfg.vocab_size, size=(B, S)).astype(np.int32)
        executor = ModelExecutor(
            params, cfg, num_slots=B, max_len=S + T, decode_steps=2
        )
        faulty = FaultyExecutor(executor, "step-hbm-oom", at_step=1)
        eng = self._engine(faulty)
        reqs = [eng.submit(prompts[i], T, request_id=f"r{i}") for i in range(B)]
        self._drive(eng)
        assert reqs[2].state == RequestState.FAILED  # youngest implicated
        assert reqs[2].cause == "hbm-oom"
        for i in (0, 1):
            assert reqs[i].state == RequestState.FINISHED
            solo = np.asarray(
                generate(
                    params, jnp.asarray(prompts[i : i + 1]), cfg,
                    max_new_tokens=T, max_len=S + T,
                )
            )[0]
            np.testing.assert_array_equal(np.asarray(reqs[i].output_tokens), solo)

    def test_overlap_drain_ledger_lands_preempted_with_all_terminal(self):
        """The deferred drain/SIGTERM acceptance at the serve-loop level:
        a lifecycle cancel mid-serve in OVERLAP mode still lands an honest
        PREEMPTED row, every request terminal, and the fence means no
        in-flight token was silently dropped before the drain decisions."""
        from tpu_nexus.workload.serve import run_serve_engine

        store = _seeded_store()
        lifecycle = LifecycleContext()
        cfg = _serve_cfg(overlap_dispatch=True, decode_steps=2, gen_tokens=24)

        def prompts():
            rng = np.random.default_rng(3)
            n = 0
            while True:
                if n == 2:  # warmup batch + round-1 batch delivered
                    lifecycle.cancel(reason="SIGTERM")
                yield rng.integers(1, 64, size=(cfg.batch_size, cfg.prompt_len))
                n += 1

        summary = run_serve_engine(
            cfg, store=store, ctx=CTX, prompts=prompts(), lifecycle=lifecycle
        )
        assert summary["drained"] is True
        row = store.read_checkpoint(CTX.algorithm, CTX.run_id)
        assert row.lifecycle_stage == LifecycleStage.PREEMPTED
        assert "SIGTERM" in row.algorithm_failure_cause
        details = json.loads(row.algorithm_failure_details)
        assert sum(details["retired_causes"].values()) == summary["requests"]
