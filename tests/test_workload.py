"""Workload harness tests: sharded training convergence, ledger cooperation,
tensor checkpoint restart-from-step, fault injection."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
from tpu_nexus.models import LlamaConfig
from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
from tpu_nexus.parallel.distributed import ProcessContext
from tpu_nexus.workload.data import synthetic_mnist, synthetic_tokens
from tpu_nexus.workload.faults import ENV_FAULT_MODE, ENV_FAULT_STEP
from tpu_nexus.workload.harness import WorkloadConfig, run_workload
from tpu_nexus.workload.train import (
    TrainConfig,
    init_train_state,
    make_train_step,
    next_token_loss,
)

CTX = ProcessContext(run_id="run-1", algorithm="llama-pretrain", process_id=0, num_processes=1, coordinator=None)


def tiny_workload(**over):
    from tpu_nexus.workload.health import HealthConfig

    base = dict(
        model=LlamaConfig.tiny(),
        train=TrainConfig(warmup_steps=2, total_steps=50, learning_rate=1e-3),
        mesh=MeshSpec(fsdp=2, sp=2, tp=2),
        batch_size=4,
        seq_len=32,
        steps=10,
        heartbeat_every=2,
        # this mesh hits the documented jax-0.4.37 sp x tp NaN (see
        # .claude/skills/verify/SKILL.md): the loss is NaN on this IMAGE, not
        # in the code under test.  The health sentinel would (correctly)
        # refuse to train through it, so these ledger/restart tests pin it
        # off; tests/test_training_health.py owns the sentinel's behavior.
        health=HealthConfig(enabled=False),
    )
    base.update(over)
    return WorkloadConfig(**base)


class TestTrainStep:
    def test_loss_decreases_sharded(self):
        cfg = LlamaConfig.tiny()
        tcfg = TrainConfig(warmup_steps=2, total_steps=100, learning_rate=3e-3)
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        data = synthetic_tokens(8, 64, cfg.vocab_size, seed=0)
        losses = []
        with mesh:
            for _ in range(30):
                state, m = step_fn(state, jnp.asarray(next(data)))
                losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] - 0.3, losses[:3] + losses[-3:]
        assert int(state["step"]) == 30

    @pytest.mark.parametrize("optimizer", ["adamw-bf16", "adafactor"])
    def test_compressed_optimizer_states_train(self, optimizer):
        """TrainConfig.optimizer knob (VERDICT r3 #4): bf16-moment adamw and
        adafactor both train the tiny model down, and the bf16 variant
        really stores its moments in bf16 (the memory the knob exists to
        free)."""
        cfg = LlamaConfig.tiny()
        tcfg = TrainConfig(
            warmup_steps=2, total_steps=100, learning_rate=3e-3, optimizer=optimizer
        )
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        if optimizer == "adamw-bf16":
            moment_dtypes = {
                leaf.dtype
                for leaf in jax.tree.leaves(state["opt_state"])
                if hasattr(leaf, "dtype") and leaf.ndim > 0
            }
            assert any(d == jnp.bfloat16 for d in moment_dtypes), moment_dtypes
        step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        data = synthetic_tokens(8, 64, cfg.vocab_size, seed=0)
        losses = []
        with mesh:
            for _ in range(30):
                state, m = step_fn(state, jnp.asarray(next(data)))
                losses.append(float(m["loss"]))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0] - 0.3, losses[:3] + losses[-3:]

    def test_adamw_bf16_tracks_adamw_trajectory(self):
        """The bf16-moment storage must not meaningfully bend the training
        trajectory: after 10 steps on identical data the loss gap vs f32
        adamw stays small."""
        cfg = LlamaConfig.tiny()
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        final = {}
        for optimizer in ("adamw", "adamw-bf16"):
            tcfg = TrainConfig(
                warmup_steps=2, total_steps=100, learning_rate=3e-3, optimizer=optimizer
            )
            state = init_train_state(
                jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP
            )
            step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
            data = synthetic_tokens(8, 64, cfg.vocab_size, seed=0)
            with mesh:
                for _ in range(10):
                    state, m = step_fn(state, jnp.asarray(next(data)))
            final[optimizer] = float(m["loss"])
        assert abs(final["adamw"] - final["adamw-bf16"]) < 0.05, final

    def test_unknown_optimizer_rejected(self):
        from tpu_nexus.workload.train import make_optimizer

        with pytest.raises(ValueError, match="unknown TrainConfig.optimizer"):
            make_optimizer(TrainConfig(optimizer="sgd"))

    def test_qkv_remat_policy_matches_attn_out(self):
        """The new 'qkv' remat policy is numerics-neutral (it only changes
        WHAT the backward replays): one train step agrees with attn_out."""
        final = {}
        for policy in ("attn_out", "qkv"):
            cfg = dataclasses.replace(
                LlamaConfig.tiny(), remat=True, remat_policy=policy,
                dtype=jnp.float32, param_dtype=jnp.float32,
            )
            tcfg = TrainConfig(warmup_steps=2, total_steps=100, learning_rate=3e-3)
            mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
            state = init_train_state(
                jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP
            )
            step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
            data = synthetic_tokens(8, 64, cfg.vocab_size, seed=0)
            with mesh:
                state, m = step_fn(state, jnp.asarray(next(data)))
            final[policy] = float(m["loss"])
        assert abs(final["attn_out"] - final["qkv"]) < 1e-5, final

    def test_params_actually_sharded(self):
        cfg = LlamaConfig.tiny()
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(
            jax.random.PRNGKey(0), cfg, TrainConfig(), mesh, LOGICAL_RULES_FSDP_TP
        )
        wq = state["params"]["layers"]["wq"]  # [L, E, H, D] -> embed on fsdp, heads on tp
        shard = wq.addressable_shards[0].data
        assert shard.shape[1] == wq.shape[1] // 4
        assert shard.shape[2] == wq.shape[2] // 2
        # adam mu mirrors the param sharding
        mu = jax.tree.leaves(state["opt_state"])  # find matching leaf by shape
        mu_wq = [x for x in mu if getattr(x, "shape", None) == wq.shape]
        assert mu_wq and mu_wq[0].addressable_shards[0].data.shape == shard.shape

    def test_next_token_loss_masks_shift(self):
        logits = jnp.zeros((1, 4, 8))
        tokens = jnp.array([[1, 2, 3, 4]])
        loss, aux = next_token_loss(logits, tokens)
        # uniform logits -> CE = log(8)
        assert abs(float(loss) - 2.0794) < 1e-3


class TestHarness:
    def test_end_to_end_ledger_cooperation(self):
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm=CTX.algorithm, id=CTX.run_id, lifecycle_stage=LifecycleStage.BUFFERED)
        )
        result = run_workload(tiny_workload(), store=store, ctx=CTX)
        assert result["final_step"] == 10
        cp = store.read_checkpoint(CTX.algorithm, CTX.run_id)
        assert cp.lifecycle_stage == LifecycleStage.COMPLETED
        # per-chip heartbeats for all 8 virtual devices
        assert cp.per_chip_steps == {f"host0/chip{i}": 10 for i in range(8)}

    def test_cancelled_run_not_resurrected(self):
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm=CTX.algorithm, id=CTX.run_id, lifecycle_stage=LifecycleStage.CANCELLED)
        )
        run_workload(tiny_workload(steps=4, heartbeat_every=2), store=store, ctx=CTX)
        cp = store.read_checkpoint(CTX.algorithm, CTX.run_id)
        assert cp.lifecycle_stage == LifecycleStage.CANCELLED
        assert cp.per_chip_steps == {}

    def test_checkpoint_restart_from_step(self, tmp_path):
        d = str(tmp_path / "ckpt")
        cfg1 = tiny_workload(steps=4, checkpoint_every=2, checkpoint_dir=d)
        r1 = run_workload(cfg1, ctx=CTX)
        assert r1["final_step"] == 4
        # second run resumes from step 4, not 0
        cfg2 = tiny_workload(steps=6, checkpoint_every=2, checkpoint_dir=d)
        r2 = run_workload(cfg2, ctx=CTX)
        assert r2["final_step"] == 6

    def test_fault_injection_xla_abort(self, monkeypatch):
        monkeypatch.setenv(ENV_FAULT_MODE, "xla-abort")
        monkeypatch.setenv(ENV_FAULT_STEP, "2")
        with pytest.raises(RuntimeError, match="XLA compilation aborted"):
            run_workload(tiny_workload(), ctx=CTX)

    def test_fault_injection_hbm_oom(self, monkeypatch):
        monkeypatch.setenv(ENV_FAULT_MODE, "hbm-oom")
        monkeypatch.setenv(ENV_FAULT_STEP, "0")
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            run_workload(tiny_workload(), ctx=CTX)

    def test_fault_injection_ici(self, monkeypatch):
        """The ici wording raises out of the loop and classifies to the ICI
        decision (nxlint NX009: every registered fault mode is drilled)."""
        from tpu_nexus.supervisor.taxonomy import DecisionAction, classify_tpu_failure

        monkeypatch.setenv(ENV_FAULT_MODE, "ici")
        monkeypatch.setenv(ENV_FAULT_STEP, "1")
        with pytest.raises(RuntimeError, match="ICI link failure") as ei:
            run_workload(tiny_workload(), ctx=CTX)
        assert classify_tpu_failure(str(ei.value)) == DecisionAction.TO_FAIL_ICI_LINK_DOWN


class TestData:
    def test_synthetic_tokens_deterministic(self):
        a = next(synthetic_tokens(2, 8, 100, seed=1))
        b = next(synthetic_tokens(2, 8, 100, seed=1))
        assert (a == b).all()
        assert a.shape == (2, 8) and a.dtype.name == "int32"

    def test_synthetic_mnist_separable(self):
        x, y = next(synthetic_mnist(16, seed=0))
        assert x.shape == (16, 784) and y.shape == (16,)


class TestTokenCorpus:
    """The .npy memory-mapped corpus loader and its harness wiring."""

    def _corpus(self, tmp_path, n=4096, vocab=256):
        from tpu_nexus.workload.data import write_token_npy

        rng = np.random.default_rng(0)
        path = str(tmp_path / "corpus.npy")
        write_token_npy(path, rng.integers(0, vocab, size=n, dtype=np.uint16))
        return path

    def test_batches_are_deterministic_windows(self, tmp_path):
        from tpu_nexus.workload.data import token_file_batches

        path = self._corpus(tmp_path)
        a = token_file_batches(path, batch=4, seq_len=32, seed=3)
        b = token_file_batches(path, batch=4, seq_len=32, seed=3)
        first_a, first_b = next(a), next(b)
        np.testing.assert_array_equal(first_a, first_b)  # resume contract
        assert first_a.shape == (4, 32) and first_a.dtype == np.int32
        corpus = np.load(path)
        # every row is a literal window of the corpus
        row = first_a[0]
        starts = np.flatnonzero(corpus[: -32].astype(np.int32) == row[0])
        assert any((corpus[s : s + 32].astype(np.int32) == row).all() for s in starts)
        # different seed -> different sample
        c = next(token_file_batches(path, batch=4, seq_len=32, seed=4))
        assert not np.array_equal(first_a, c)

    def test_final_window_reachable_and_range_split(self, tmp_path):
        from tpu_nexus.workload.data import token_file_batches, write_token_npy

        path = str(tmp_path / "c.npy")
        write_token_npy(path, np.arange(40, dtype=np.uint16))
        # corpus of exactly seq_len: one valid window, must not be rejected
        one = next(token_file_batches(path, batch=2, seq_len=40, seed=0))
        np.testing.assert_array_equal(one[0], np.arange(40))
        # the final token is reachable (inclusive window bound)
        seen_last = False
        stream = token_file_batches(path, batch=8, seq_len=8, seed=1)
        for _ in range(50):
            if (next(stream)[:, -1] == 39).any():
                seen_last = True
                break
        assert seen_last
        # range split: windows stay wholly inside [start, end)
        tail = token_file_batches(path, batch=16, seq_len=8, seed=2, start=32)
        b = next(tail)
        assert b.min() >= 32 and b.max() == 39
        head = token_file_batches(path, batch=16, seq_len=8, seed=2, end=32)
        assert next(head).max() < 32

    def test_rejects_bad_corpus(self, tmp_path):
        from tpu_nexus.workload.data import token_file_batches, write_token_npy

        path = str(tmp_path / "bad.npy")
        np.save(path, np.zeros((4, 4), np.int32))
        with pytest.raises(ValueError, match="1-D integer"):
            token_file_batches(path, 2, 8)
        with pytest.raises(ValueError, match="1-D integer"):
            write_token_npy(str(tmp_path / "f.npy"), np.zeros((3, 3), np.int32))
        short = str(tmp_path / "short.npy")
        np.save(short, np.zeros((4,), np.int32))
        with pytest.raises(ValueError, match="< seq_len"):
            token_file_batches(short, 2, 8)

    def test_harness_trains_from_corpus_with_eval(self, tmp_path):
        """End to end: NEXUS_DATA_PATH-style corpus + periodic eval — the
        summary carries a finite eval_loss and the run completes."""
        path = self._corpus(tmp_path)
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(
                algorithm=CTX.algorithm, id=CTX.run_id,
                lifecycle_stage=LifecycleStage.BUFFERED,
            )
        )
        cfg = tiny_workload(data_path=path, eval_every=4, eval_steps=2, steps=8)
        result = run_workload(cfg, store=store, ctx=CTX)
        assert result["final_step"] == 8
        assert np.isfinite(result["eval_loss"])
        row = store.read_checkpoint(CTX.algorithm, CTX.run_id)
        assert row.lifecycle_stage == LifecycleStage.COMPLETED

    def test_data_path_refused_for_non_lm_adapter(self, tmp_path):
        from tpu_nexus.models import MnistConfig

        path = self._corpus(tmp_path)
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(
                algorithm=CTX.algorithm, id=CTX.run_id,
                lifecycle_stage=LifecycleStage.BUFFERED,
            )
        )
        cfg = tiny_workload(model=MnistConfig(), data_path=path, mesh=MeshSpec(fsdp=-1))
        with pytest.raises((ValueError, RuntimeError), match="token-batch"):
            run_workload(cfg, store=store, ctx=CTX)
