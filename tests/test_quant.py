"""Int8 weight-only quantization: numerics, pytree mechanics, and the
zero-change flow through the existing forward/decode paths."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.models import LlamaConfig, MoeConfig
from tpu_nexus.models.generate import generate
from tpu_nexus.models.llama import llama_forward, llama_init
from tpu_nexus.models.moe import moe_hidden, moe_init
from tpu_nexus.models.quant import quantize_params, quantize_tensor, quantized_bytes


class TestQTensor:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 4, 16))
        qt = quantize_tensor(w, (-3,))
        deq = np.asarray(qt.astype(jnp.float32))
        # symmetric per-channel int8: error < scale/2 per element
        scale = np.asarray(qt.s)
        assert np.all(np.abs(deq - np.asarray(w)) <= scale / 2 + 1e-7)
        assert qt.q.dtype == jnp.int8 and qt.s.shape == (1, 4, 16)

    def test_is_pytree_and_scans(self):
        """Stacked QTensors slice per layer under lax.scan like any weight."""
        w = jax.random.normal(jax.random.PRNGKey(0), (3, 8, 8))
        qt = quantize_tensor(w, (-2,))

        def body(c, layer_qt):
            return c @ layer_qt.astype(jnp.float32), None

        out, _ = jax.lax.scan(body, jnp.eye(8), qt)
        ref = jnp.eye(8)
        for i in range(3):
            ref = ref @ (np.asarray(w[i] / qt.s[i]).round().clip(-127, 127) * np.asarray(qt.s[i]))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


class TestQuantizedModels:
    def test_llama_forward_close_and_decodes(self):
        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        lf = np.asarray(llama_forward(params, tokens, cfg))
        lq = np.asarray(llama_forward(qparams, tokens, cfg))
        rel = np.abs(lq - lf).max() / (np.abs(lf).max() + 1e-9)
        assert rel < 0.05, rel
        toks = generate(qparams, tokens, cfg, max_new_tokens=4)
        assert toks.shape == (2, 4) and int(toks.max()) < cfg.vocab_size

    def test_moe_forward_close(self):
        cfg = dataclasses.replace(MoeConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = moe_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        hf, _ = moe_hidden(params, tokens, cfg)
        hq, _ = moe_hidden(qparams, tokens, cfg)
        rel = np.abs(np.asarray(hq - hf)).max() / (np.abs(np.asarray(hf)).max() + 1e-9)
        assert rel < 0.1, rel

    def test_bytes_shrink(self):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        assert quantized_bytes(qparams) < 0.6 * quantized_bytes(params)

    def test_serve_int8_mode(self):
        from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
        from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
        from tpu_nexus.parallel.distributed import ProcessContext
        from tpu_nexus.workload.serve import ServeConfig, run_serving

        ctx = ProcessContext(
            run_id="q-1", algorithm="a", process_id=0, num_processes=1, coordinator=None
        )
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm="a", id="q-1", lifecycle_stage=LifecycleStage.BUFFERED)
        )
        cfg = ServeConfig(
            model=LlamaConfig.tiny(), batch_size=2, prompt_len=8, gen_tokens=4,
            rounds=2, quantize="int8",
        )
        summary = run_serving(cfg, store=store, ctx=ctx)
        assert summary["last_tokens_shape"] == (2, 4)
        assert store.read_checkpoint("a", "q-1").lifecycle_stage == LifecycleStage.COMPLETED
        with pytest.raises(ValueError, match="quantize mode"):
            run_serving(
                dataclasses.replace(cfg, quantize="fp4"), store=store, ctx=ctx
            )
