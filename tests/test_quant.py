"""Int8 weight-only quantization: numerics, pytree mechanics, and the
zero-change flow through the existing forward/decode paths."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.models import LlamaConfig, MoeConfig
from tpu_nexus.models.generate import generate
from tpu_nexus.models.llama import llama_forward, llama_init
from tpu_nexus.models.moe import moe_hidden, moe_init
from tpu_nexus.models.quant import quantize_params, quantize_tensor, quantized_bytes


class TestQTensor:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 4, 16))
        qt = quantize_tensor(w, (-3,))
        deq = np.asarray(qt.astype(jnp.float32))
        # symmetric per-channel int8: error < scale/2 per element
        scale = np.asarray(qt.s)
        assert np.all(np.abs(deq - np.asarray(w)) <= scale / 2 + 1e-7)
        assert qt.q.dtype == jnp.int8 and qt.s.shape == (1, 4, 16)

    def test_is_pytree_and_scans(self):
        """Stacked QTensors slice per layer under lax.scan like any weight."""
        w = jax.random.normal(jax.random.PRNGKey(0), (3, 8, 8))
        qt = quantize_tensor(w, (-2,))

        def body(c, layer_qt):
            return c @ layer_qt.astype(jnp.float32), None

        out, _ = jax.lax.scan(body, jnp.eye(8), qt)
        ref = jnp.eye(8)
        for i in range(3):
            ref = ref @ (np.asarray(w[i] / qt.s[i]).round().clip(-127, 127) * np.asarray(qt.s[i]))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


class TestQuantizedModels:
    def test_llama_forward_close_and_decodes(self):
        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        lf = np.asarray(llama_forward(params, tokens, cfg))
        lq = np.asarray(llama_forward(qparams, tokens, cfg))
        rel = np.abs(lq - lf).max() / (np.abs(lf).max() + 1e-9)
        assert rel < 0.05, rel
        toks = generate(qparams, tokens, cfg, max_new_tokens=4)
        assert toks.shape == (2, 4) and int(toks.max()) < cfg.vocab_size

    def test_moe_forward_close(self):
        cfg = dataclasses.replace(MoeConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = moe_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        hf, _ = moe_hidden(params, tokens, cfg)
        hq, _ = moe_hidden(qparams, tokens, cfg)
        rel = np.abs(np.asarray(hq - hf)).max() / (np.abs(np.asarray(hf)).max() + 1e-9)
        assert rel < 0.1, rel

    def test_bytes_shrink(self):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        assert quantized_bytes(qparams) < 0.6 * quantized_bytes(params)

    def test_serve_int8_mode(self):
        from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
        from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
        from tpu_nexus.parallel.distributed import ProcessContext
        from tpu_nexus.workload.serve import ServeConfig, run_serving

        ctx = ProcessContext(
            run_id="q-1", algorithm="a", process_id=0, num_processes=1, coordinator=None
        )
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm="a", id="q-1", lifecycle_stage=LifecycleStage.BUFFERED)
        )
        cfg = ServeConfig(
            model=LlamaConfig.tiny(), batch_size=2, prompt_len=8, gen_tokens=4,
            rounds=2, quantize="int8",
        )
        summary = run_serving(cfg, store=store, ctx=ctx)
        assert summary["last_tokens_shape"] == (2, 4)
        assert store.read_checkpoint("a", "q-1").lifecycle_stage == LifecycleStage.COMPLETED
        with pytest.raises(ValueError, match="quantize mode"):
            run_serving(
                dataclasses.replace(cfg, quantize="fp4"), store=store, ctx=ctx
            )


class TestQuantQuality:
    def test_heldout_perplexity_delta_bounded(self, tmp_path):
        """The serving speedup must carry a QUALITY number (VERDICT r3 #8):
        train on a real mmap token corpus, then evaluate held-out
        perplexity through train.make_eval_step with full-precision vs
        int8 weight-only params — the delta is gated, not anecdotal."""
        from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
        from tpu_nexus.workload.data import token_file_batches, write_token_npy
        from tpu_nexus.workload.train import (
            TrainConfig,
            init_train_state,
            make_eval_step,
            make_train_step,
        )

        vocab = 128
        rng = np.random.default_rng(0)
        # corpus with learnable structure: noisy affine bigram chain — a
        # tiny model halves its perplexity on this within ~60 steps
        n = 65536
        toks = np.empty(n, np.int32)
        toks[0] = 1
        noise = rng.integers(0, 4, size=n)
        for i in range(1, n):
            toks[i] = (toks[i - 1] * 31 + 7 + noise[i]) % vocab
        path = write_token_npy(str(tmp_path / "corpus.npy"), toks)

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=vocab), dtype=jnp.float32)
        tcfg = TrainConfig(warmup_steps=5, total_steps=200, learning_rate=3e-3)
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        split = int(n * 0.9)
        train_data = token_file_batches(path, batch=8, seq_len=64, seed=1, end=split)
        with mesh:
            for _ in range(60):
                state, _ = step_fn(state, jnp.asarray(next(train_data)))

        eval_fn = make_eval_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        heldout = token_file_batches(path, batch=8, seq_len=64, seed=99, start=split)
        batches = [jnp.asarray(next(heldout)) for _ in range(8)]

        def mean_ppl(params):
            with mesh:
                ces = [float(eval_fn({"params": params}, b)["ce_loss"]) for b in batches]
            return float(np.exp(np.mean(ces)))

        ppl_full = mean_ppl(state["params"])
        ppl_int8 = mean_ppl(quantize_params(state["params"]))
        assert ppl_full < 0.8 * vocab  # the model actually learned
        rel = (ppl_int8 - ppl_full) / ppl_full
        # int8 weight-only on a TRAINED model: held-out perplexity within
        # 1% of full precision (measured +0.002%, PERF.md r4 — the bound
        # leaves ~500x headroom for noisier corpora/models)
        assert abs(rel) < 0.01, (ppl_full, ppl_int8, rel)


class TestInt8KvCache:
    """Int8 KV-cache quantization (VERDICT r4 #4): halves cache traffic and
    doubles the context budget per byte — gated on decode-path quality the
    same way the weight path is."""

    def test_cache_layout_and_decode_close(self):
        from tpu_nexus.models.generate import decode_step, prefill

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)

        cache_f, logits_f = prefill(params, tokens, cfg, max_len=24)
        cache_q, logits_q = prefill(params, tokens, cfg, max_len=24, kv_quant="int8")
        # prefill logits identical (the quantized cache is not read yet)
        np.testing.assert_allclose(np.asarray(logits_f), np.asarray(logits_q), rtol=1e-5)
        assert cache_q["k"].dtype == jnp.int8 and cache_q["v"].dtype == jnp.int8
        assert cache_q["k_s"].shape == cache_q["k"].shape[:-1] + (1,)

        nxt = jnp.argmax(logits_f, axis=-1).astype(tokens.dtype)
        pos = jnp.asarray(16, jnp.int32)
        lf, _ = decode_step(params, cache_f, nxt, pos, cfg)
        lq, _ = decode_step(params, cache_q, nxt, pos, cfg)
        rel = np.abs(np.asarray(lq - lf)).max() / (np.abs(np.asarray(lf)).max() + 1e-9)
        # per-slot symmetric int8 on the cache: logits within a few percent
        assert rel < 0.05, rel

    def test_generate_and_ragged_with_int8_kv(self):
        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        toks = generate(params, prompt, cfg, max_new_tokens=4, kv_quant="int8")
        assert toks.shape == (2, 4) and int(toks.max()) < cfg.vocab_size
        # ragged right-padded batches compose with the quantized cache
        lengths = jnp.asarray([5, 8], jnp.int32)
        toks = generate(
            params, prompt, cfg, max_new_tokens=4,
            prompt_lengths=lengths, kv_quant="int8",
        )
        assert toks.shape == (2, 4)
        with pytest.raises(ValueError, match="kv_quant"):
            generate(params, prompt, cfg, max_new_tokens=2, kv_quant="fp4")

    def test_decode_path_perplexity_gate(self, tmp_path):
        """Teacher-forced scoring THROUGH the decode path (prefill one
        token, decode_step over the rest — the exact code serving runs):
        int8 KV within 1% of the full-precision cache on a TRAINED model,
        and composed int8 weights + int8 KV within 2%."""
        from tpu_nexus.models.generate import teacher_forced_decode_ce
        from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
        from tpu_nexus.workload.data import token_file_batches, write_token_npy
        from tpu_nexus.workload.train import TrainConfig, init_train_state, make_train_step

        vocab = 128
        rng = np.random.default_rng(0)
        n = 65536
        toks = np.empty(n, np.int32)
        toks[0] = 1
        noise = rng.integers(0, 4, size=n)
        for i in range(1, n):
            toks[i] = (toks[i - 1] * 31 + 7 + noise[i]) % vocab
        path = write_token_npy(str(tmp_path / "corpus.npy"), toks)

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=vocab), dtype=jnp.float32)
        tcfg = TrainConfig(warmup_steps=5, total_steps=200, learning_rate=3e-3)
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        split = int(n * 0.9)
        train_data = token_file_batches(path, batch=8, seq_len=64, seed=1, end=split)
        with mesh:
            for _ in range(60):
                state, _ = step_fn(state, jnp.asarray(next(train_data)))
        params = jax.tree.map(lambda a: np.asarray(a), state["params"])  # unshard

        import functools

        @functools.partial(jax.jit, static_argnames=("kv_quant",))
        def decode_ce(params, batch, kv_quant=""):
            return teacher_forced_decode_ce(params, batch, cfg, kv_quant=kv_quant)

        heldout = token_file_batches(path, batch=8, seq_len=64, seed=99, start=split)
        batches = [jnp.asarray(next(heldout)) for _ in range(4)]

        def ppl(params, kv_quant=""):
            return float(np.exp(np.mean([
                float(decode_ce(params, b, kv_quant=kv_quant)) for b in batches
            ])))

        ppl_full = ppl(params)
        assert ppl_full < 0.8 * vocab  # the decode-path scorer sees a trained model
        ppl_kv8 = ppl(params, kv_quant="int8")
        rel_kv = (ppl_kv8 - ppl_full) / ppl_full
        assert abs(rel_kv) < 0.01, (ppl_full, ppl_kv8, rel_kv)
        qparams = quantize_params(params)
        ppl_both = ppl(qparams, kv_quant="int8")
        rel_both = (ppl_both - ppl_full) / ppl_full
        # the two quantizations must COMPOSE without compounding blowup
        assert abs(rel_both) < 0.02, (ppl_full, ppl_both, rel_both)

    def test_moe_decode_path_with_int8_kv(self):
        """The MoE family through the quantized decode path: generate works
        with int8 weights + int8 KV, and the decode-path CE stays close to
        full precision (random-weight probe; deployment-scale gating is the
        same machinery as the Llama gate)."""
        from tpu_nexus.models.generate import teacher_forced_decode_ce

        cfg = dataclasses.replace(MoeConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = moe_init(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        toks = generate(
            quantize_params(params), prompt, cfg, max_new_tokens=4, kv_quant="int8"
        )
        assert toks.shape == (2, 4)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 24), 0, cfg.vocab_size)
        ce_full = float(teacher_forced_decode_ce(params, tokens, cfg))
        ce_kv8 = float(teacher_forced_decode_ce(params, tokens, cfg, kv_quant="int8"))
        assert abs(ce_kv8 - ce_full) / ce_full < 0.02, (ce_full, ce_kv8)
