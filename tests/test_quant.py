"""Int8 weight-only quantization: numerics, pytree mechanics, and the
zero-change flow through the existing forward/decode paths."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.models import LlamaConfig, MoeConfig
from tpu_nexus.models.generate import generate
from tpu_nexus.models.llama import llama_forward, llama_init
from tpu_nexus.models.moe import moe_hidden, moe_init
from tpu_nexus.models.quant import quantize_params, quantize_tensor, quantized_bytes


class TestQTensor:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 4, 16))
        qt = quantize_tensor(w, (-3,))
        deq = np.asarray(qt.astype(jnp.float32))
        # symmetric per-channel int8: error < scale/2 per element
        scale = np.asarray(qt.s)
        assert np.all(np.abs(deq - np.asarray(w)) <= scale / 2 + 1e-7)
        assert qt.q.dtype == jnp.int8 and qt.s.shape == (1, 4, 16)

    def test_is_pytree_and_scans(self):
        """Stacked QTensors slice per layer under lax.scan like any weight."""
        w = jax.random.normal(jax.random.PRNGKey(0), (3, 8, 8))
        qt = quantize_tensor(w, (-2,))

        def body(c, layer_qt):
            return c @ layer_qt.astype(jnp.float32), None

        out, _ = jax.lax.scan(body, jnp.eye(8), qt)
        ref = jnp.eye(8)
        for i in range(3):
            ref = ref @ (np.asarray(w[i] / qt.s[i]).round().clip(-127, 127) * np.asarray(qt.s[i]))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


class TestQuantizedModels:
    def test_llama_forward_close_and_decodes(self):
        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        lf = np.asarray(llama_forward(params, tokens, cfg))
        lq = np.asarray(llama_forward(qparams, tokens, cfg))
        rel = np.abs(lq - lf).max() / (np.abs(lf).max() + 1e-9)
        assert rel < 0.05, rel
        toks = generate(qparams, tokens, cfg, max_new_tokens=4)
        assert toks.shape == (2, 4) and int(toks.max()) < cfg.vocab_size

    def test_moe_forward_close(self):
        cfg = dataclasses.replace(MoeConfig.tiny(vocab_size=64), dtype=jnp.float32)
        params = moe_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        hf, _ = moe_hidden(params, tokens, cfg)
        hq, _ = moe_hidden(qparams, tokens, cfg)
        rel = np.abs(np.asarray(hq - hf)).max() / (np.abs(np.asarray(hf)).max() + 1e-9)
        assert rel < 0.1, rel

    def test_bytes_shrink(self):
        cfg = LlamaConfig.tiny()
        params = llama_init(jax.random.PRNGKey(0), cfg)
        qparams = quantize_params(params)
        assert quantized_bytes(qparams) < 0.6 * quantized_bytes(params)

    def test_serve_int8_mode(self):
        from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
        from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
        from tpu_nexus.parallel.distributed import ProcessContext
        from tpu_nexus.workload.serve import ServeConfig, run_serving

        ctx = ProcessContext(
            run_id="q-1", algorithm="a", process_id=0, num_processes=1, coordinator=None
        )
        store = InMemoryCheckpointStore()
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm="a", id="q-1", lifecycle_stage=LifecycleStage.BUFFERED)
        )
        cfg = ServeConfig(
            model=LlamaConfig.tiny(), batch_size=2, prompt_len=8, gen_tokens=4,
            rounds=2, quantize="int8",
        )
        summary = run_serving(cfg, store=store, ctx=ctx)
        assert summary["last_tokens_shape"] == (2, 4)
        assert store.read_checkpoint("a", "q-1").lifecycle_stage == LifecycleStage.COMPLETED
        with pytest.raises(ValueError, match="quantize mode"):
            run_serving(
                dataclasses.replace(cfg, quantize="fp4"), store=store, ctx=ctx
            )


class TestQuantQuality:
    def test_heldout_perplexity_delta_bounded(self, tmp_path):
        """The serving speedup must carry a QUALITY number (VERDICT r3 #8):
        train on a real mmap token corpus, then evaluate held-out
        perplexity through train.make_eval_step with full-precision vs
        int8 weight-only params — the delta is gated, not anecdotal."""
        from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
        from tpu_nexus.workload.data import token_file_batches, write_token_npy
        from tpu_nexus.workload.train import (
            TrainConfig,
            init_train_state,
            make_eval_step,
            make_train_step,
        )

        vocab = 128
        rng = np.random.default_rng(0)
        # corpus with learnable structure: noisy affine bigram chain — a
        # tiny model halves its perplexity on this within ~60 steps
        n = 65536
        toks = np.empty(n, np.int32)
        toks[0] = 1
        noise = rng.integers(0, 4, size=n)
        for i in range(1, n):
            toks[i] = (toks[i - 1] * 31 + 7 + noise[i]) % vocab
        path = write_token_npy(str(tmp_path / "corpus.npy"), toks)

        cfg = dataclasses.replace(LlamaConfig.tiny(vocab_size=vocab), dtype=jnp.float32)
        tcfg = TrainConfig(warmup_steps=5, total_steps=200, learning_rate=3e-3)
        mesh = build_mesh(MeshSpec(fsdp=4, tp=2))
        state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        split = int(n * 0.9)
        train_data = token_file_batches(path, batch=8, seq_len=64, seed=1, end=split)
        with mesh:
            for _ in range(60):
                state, _ = step_fn(state, jnp.asarray(next(train_data)))

        eval_fn = make_eval_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
        heldout = token_file_batches(path, batch=8, seq_len=64, seed=99, start=split)
        batches = [jnp.asarray(next(heldout)) for _ in range(8)]

        def mean_ppl(params):
            with mesh:
                ces = [float(eval_fn({"params": params}, b)["ce_loss"]) for b in batches]
            return float(np.exp(np.mean(ces)))

        ppl_full = mean_ppl(state["params"])
        ppl_int8 = mean_ppl(quantize_params(state["params"]))
        assert ppl_full < 0.8 * vocab  # the model actually learned
        rel = (ppl_int8 - ppl_full) / ppl_full
        # int8 weight-only on a TRAINED model: held-out perplexity within
        # 1% of full precision (measured +0.002%, PERF.md r4 — the bound
        # leaves ~500x headroom for noisier corpora/models)
        assert abs(rel) < 0.01, (ppl_full, ppl_int8, rel)
