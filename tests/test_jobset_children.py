"""JobSet child-pod / child-Job event resolution (VERDICT r3 Missing #1).

For JobSet-launched multi-host runs, the JobSet controller creates a child
Job named `{run_id}-workers-0` whose pods carry BOTH backlinks:
`batch.kubernetes.io/job-name: {run_id}-workers-0` (the Job controller's)
and `jobset.sigs.k8s.io/jobset-name: {run_id}` (the JobSet controller's).
The reference maps a pod to its run via the job-name backlink alone
(services/supervisor.go:231,241,251), which for JobSet children resolves a
request id with NO ledger row — r3's supervisor then deleted the healthy
JobSet's child Job and retried forever.  These tests drive the supervisor
against a fake that plays the real controllers (FakeKubeClient's
jobset_controller mode materializes the children exactly as they label
them) and assert the jobset-name backlink wins.
"""

import asyncio
import uuid
from datetime import timedelta

from tpu_nexus.checkpoint.models import (
    JOB_LABEL_ALGORITHM_RUN,
    JOB_TEMPLATE_NAME_KEY,
    JOBSET_NAME_LABEL,
    NEXUS_COMPONENT_LABEL,
    POD_JOB_NAME_LABEL,
    CheckpointedRequest,
    LifecycleStage,
)
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.k8s.fake import FakeKubeClient
from tpu_nexus.launcher.client import Launcher
from tpu_nexus.launcher.jobset import LaunchSpec, compose_jobset
from tpu_nexus.supervisor.service import ProcessingConfig, Supervisor
from tpu_nexus.supervisor.taxonomy import MSG_DEADLINE_EXCEEDED, MSG_PREEMPTED

NS = "nexus"
ALGORITHM = "llama-multihost"


def _spec(rid, num_hosts=2):
    return LaunchSpec(
        run_id=rid,
        algorithm=ALGORITHM,
        image="tpu-nexus-workload:test",
        num_hosts=num_hosts,
        namespace=NS,
    )


def _event(reason, message, kind, obj_name):
    return {
        "kind": "Event",
        "metadata": {"name": f"evt-{reason}-{obj_name}"[:63], "namespace": NS},
        "reason": reason,
        "message": message,
        "type": "Warning",
        "involvedObject": {"kind": kind, "name": obj_name, "namespace": NS},
    }


class JobSetFixture:
    """Launch a real JobSet through the Launcher against a controller-playing
    fake; run the supervisor over the materialized children."""

    def __init__(self):
        self.store = InMemoryCheckpointStore()
        self.client = FakeKubeClient({}, jobset_controller=True)
        self.supervisor = Supervisor(
            self.client, self.store, NS, resync_period=timedelta(0)
        )
        self.supervisor.init(
            ProcessingConfig(
                failure_rate_base_delay=timedelta(milliseconds=5),
                failure_rate_max_delay=timedelta(milliseconds=50),
                rate_limit_elements_per_second=0,
                workers=4,
            )
        )
        self.ctx = LifecycleContext()
        self.task = None

    async def launch(self, rid, num_hosts=2):
        launcher = Launcher(self.client, self.store, use_jobset=True)
        await launcher.launch(_spec(rid, num_hosts))

    async def start(self):
        self.task = asyncio.create_task(self.supervisor.start(self.ctx))
        await asyncio.sleep(0.05)

    async def stop(self):
        assert await self.supervisor.idle(timeout=10)
        self.ctx.cancel()
        await self.task

    def checkpoint(self, rid):
        return self.store.read_checkpoint(ALGORITHM, rid)


async def test_controller_materializes_labeled_children():
    """The fake plays the controllers the way the real ones label things —
    the substrate every other test here rests on."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid, num_hosts=2)
    jobs, _ = await fx.client.list_objects("Job", NS)
    assert [j["metadata"]["name"] for j in jobs] == [f"{rid}-workers-0"]
    labels = jobs[0]["metadata"]["labels"]
    assert labels[JOBSET_NAME_LABEL] == rid
    assert labels[NEXUS_COMPONENT_LABEL] == JOB_LABEL_ALGORITHM_RUN  # template metadata propagated
    pods, _ = await fx.client.list_objects("Pod", NS)
    assert sorted(p["metadata"]["name"] for p in pods) == [
        f"{rid}-workers-0-0", f"{rid}-workers-0-1",
    ]
    for p in pods:
        pl = p["metadata"]["labels"]
        assert pl[POD_JOB_NAME_LABEL] == f"{rid}-workers-0"
        assert pl[JOBSET_NAME_LABEL] == rid
        assert pl[JOB_TEMPLATE_NAME_KEY] == ALGORITHM


async def test_recreate_scopes_dependents_to_namespace():
    """Jobset names are only unique per namespace: recreating one
    namespace's JobSet children must not touch (or uid-cycle) a SAME-NAMED
    jobset's children in another namespace — label-only dependent matching
    crossed that boundary."""
    client = FakeKubeClient({}, jobset_controller=True)

    def _jobset(ns):
        return {
            "kind": "JobSet",
            "metadata": {"name": "run-x", "namespace": ns, "uid": f"js-{ns}"},
            "spec": {
                "replicatedJobs": [
                    {
                        "name": "workers",
                        "replicas": 1,
                        "template": {"spec": {"parallelism": 1, "template": {}}},
                    }
                ]
            },
        }

    await client.create_object("JobSet", "ns-a", _jobset("ns-a"))
    await client.create_object("JobSet", "ns-b", _jobset("ns-b"))
    # both namespaces materialized their own children despite the shared name
    pods, _ = await client.list_objects("Pod", "")
    assert sorted((p["metadata"]["namespace"]) for p in pods) == ["ns-a", "ns-b"]
    uid_b_before = {
        p["metadata"]["name"]: p["metadata"]["uid"]
        for p in pods
        if p["metadata"]["namespace"] == "ns-b"
    }

    client.recreate_jobset_children("ns-a", "run-x")

    pods, _ = await client.list_objects("Pod", "")
    by_ns = {p["metadata"]["namespace"]: p for p in pods}
    assert set(by_ns) == {"ns-a", "ns-b"}  # neither namespace lost its pod
    # ns-b's generation is untouched; ns-a's was cycled to fresh uids
    assert by_ns["ns-b"]["metadata"]["uid"] == uid_b_before[by_ns["ns-b"]["metadata"]["name"]]
    jobs, _ = await client.list_objects("Job", "ns-a")
    assert jobs and jobs[0]["metadata"]["uid"] != "js-ns-a"


async def test_child_pod_preemption_resolves_owning_run():
    """THE r3 bug: a TPUPreempted event on a child pod must increment the
    OWNING run's restart_count — and must not delete anything."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid)
    cp = fx.checkpoint(rid).deep_copy()
    cp.lifecycle_stage = LifecycleStage.RUNNING
    fx.store.upsert_checkpoint(cp)
    await fx.start()
    fx.client.inject(
        "ADDED", "Event",
        _event("TPUPreempted", "TPU node was preempted by Cloud provider",
               "Pod", f"{rid}-workers-0-1"),
    )
    await fx.stop()
    cp = fx.checkpoint(rid)
    assert cp.lifecycle_stage == LifecycleStage.PREEMPTED
    assert cp.restart_count == 1
    assert cp.algorithm_failure_cause == MSG_PREEMPTED
    assert fx.client.deleted("Job") == []
    assert fx.client.deleted("JobSet") == []
    # and crucially: NO phantom row for the child job's name
    assert fx.store.read_checkpoint(ALGORITHM, f"{rid}-workers-0") is None


async def test_child_pod_started_marks_owning_run_running():
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid)
    await fx.start()
    fx.client.inject(
        "ADDED", "Event",
        _event("Started", "Started container algorithm", "Pod", f"{rid}-workers-0-0"),
    )
    await fx.stop()
    assert fx.checkpoint(rid).lifecycle_stage == LifecycleStage.RUNNING


async def test_child_pod_fatal_failure_deletes_owning_jobset():
    """A terminal pod failure on a child pod must delete the OWNING JobSet —
    deleting the child Job would just make the controller recreate it."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid)
    cp = fx.checkpoint(rid).deep_copy()
    cp.lifecycle_stage = LifecycleStage.RUNNING
    fx.store.upsert_checkpoint(cp)
    await fx.start()
    # enrich the cached pod with an HBM OOM termination, as the kubelet would
    pods, _ = await fx.client.list_objects("Pod", NS)
    pod = next(p for p in pods if p["metadata"]["name"] == f"{rid}-workers-0-0")
    pod["status"] = {
        "containerStatuses": [
            {
                "name": "algorithm",
                "state": {
                    "terminated": {
                        "exitCode": 137,
                        "reason": "Error",
                        "message": "RESOURCE_EXHAUSTED: HBM exhausted on device 2",
                    }
                },
            }
        ]
    }
    fx.client.inject("MODIFIED", "Pod", pod)
    fx.client.inject(
        "ADDED", "Event",
        _event("Failed", "Pod failed", "Pod", f"{rid}-workers-0-0"),
    )
    await fx.stop()
    cp = fx.checkpoint(rid)
    assert cp.lifecycle_stage == LifecycleStage.FAILED
    assert "HBM" in cp.algorithm_failure_cause
    assert fx.client.deleted("JobSet") == [rid]
    assert fx.client.deleted("Job") == []  # never the child


async def test_child_job_backoff_limit_resolves_and_deletes_jobset():
    """Child-Job events (the Job controller's own signals) resolve to the
    owning run via the jobset-name label on the child Job."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid)
    cp = fx.checkpoint(rid).deep_copy()
    cp.lifecycle_stage = LifecycleStage.RUNNING
    fx.store.upsert_checkpoint(cp)
    await fx.start()
    fx.client.inject(
        "ADDED", "Event",
        _event("BackoffLimitExceeded", "Job has reached the specified backoff limit",
               "Job", f"{rid}-workers-0"),
    )
    await fx.stop()
    cp = fx.checkpoint(rid)
    assert cp.lifecycle_stage == LifecycleStage.DEADLINE_EXCEEDED
    assert cp.algorithm_failure_cause == MSG_DEADLINE_EXCEEDED
    assert fx.client.deleted("JobSet") == [rid]


async def test_child_pod_event_without_ledger_row_deletes_owning_jobset():
    """Missing-checkpoint path (reference services/supervisor.go:265-273)
    generalized: the orphan delete must target the top-level JobSet, not the
    child Job."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    # materialize the JobSet directly — no ledger row at all
    manifest = compose_jobset(_spec(rid))
    await fx.client.create_object("JobSet", NS, manifest)
    await fx.start()
    fx.client.inject(
        "ADDED", "Event",
        _event("Failed", "Pod failed", "Pod", f"{rid}-workers-0-0"),
    )
    # the missing-row path raises for backoff re-delivery (reference parity),
    # so poll-with-deadline for the delete instead of waiting for idle
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline and rid not in fx.client.deleted("JobSet"):
        await asyncio.sleep(0.01)
    assert fx.client.deleted("JobSet") == [rid]
    # retries after the JobSet is gone may fall back to a (NotFound, harmless)
    # Job delete on the run id — but the CHILD job must never be targeted
    assert f"{rid}-workers-0" not in fx.client.deleted("Job")
    fx.ctx.cancel()
    await fx.task


def _recreate_children(fx, rid):
    """The JobSet Recreate policy after a preemption: same names, fresh uids
    (a new generation) — now played by the fake controller itself."""
    fx.client.recreate_jobset_children(NS, rid)


async def test_restart_budget_exhaustion_goes_terminal():
    """VERDICT r3 weak #6: the launcher composes failurePolicy.maxRestarts=3
    but nothing capped the ledger's restart accounting — a preemption loop
    never went terminal.  Drive 4 distinct preemption incidents (the JobSet
    controller recreating the children — fresh pod generation — and the
    harness heartbeating RUNNING between them): the first 3 count as
    restarts; the 4th lands DEADLINE_EXCEEDED with a trace explaining the
    spent budget, and the JobSet is deleted."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid)
    cp = fx.checkpoint(rid).deep_copy()
    cp.lifecycle_stage = LifecycleStage.RUNNING
    fx.store.upsert_checkpoint(cp)
    await fx.start()

    for incident in range(1, 4):
        fx.client.inject(
            "ADDED", "Event",
            _event("TPUPreempted", f"TPU node preempted (incident {incident})",
                   "Pod", f"{rid}-workers-0-0"),
        )
        assert await fx.supervisor.idle(timeout=10)
        cp = fx.checkpoint(rid)
        assert cp.lifecycle_stage == LifecycleStage.PREEMPTED
        assert cp.restart_count == incident, (incident, cp.restart_count)
        # controller recreates the workers (new generation); harness
        # heartbeats RUNNING again
        _recreate_children(fx, rid)
        cp = cp.deep_copy()
        cp.lifecycle_stage = LifecycleStage.RUNNING
        fx.store.upsert_checkpoint(cp)
        await asyncio.sleep(0.01)

    fx.client.inject(
        "ADDED", "Event",
        _event("TPUPreempted", "TPU node preempted (incident 4)", "Pod", f"{rid}-workers-0-0"),
    )
    await fx.stop()
    cp = fx.checkpoint(rid)
    assert cp.lifecycle_stage == LifecycleStage.DEADLINE_EXCEEDED
    assert cp.restart_count == 3  # never advertises a 4th restart
    assert cp.algorithm_failure_cause == MSG_DEADLINE_EXCEEDED
    assert "maxRestarts=3" in cp.algorithm_failure_details
    assert fx.client.deleted("JobSet") == [rid]


async def test_same_incident_fanout_does_not_escalate_at_budget():
    """The Nth host's event for the FINAL allowed restart must stay a
    suppressed duplicate, not tip the run over the budget."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid)
    cp = fx.checkpoint(rid).deep_copy()
    cp.lifecycle_stage = LifecycleStage.RUNNING
    cp.restart_count = 2  # two incidents already recorded
    fx.store.upsert_checkpoint(cp)
    await fx.start()
    # the 3rd (last allowed) incident fans out to both hosts within seconds
    for i in range(2):
        fx.client.inject(
            "ADDED", "Event",
            _event("TPUPreempted", "TPU node preempted", "Pod", f"{rid}-workers-0-{i}"),
        )
    await fx.stop()
    cp = fx.checkpoint(rid)
    assert cp.lifecycle_stage == LifecycleStage.PREEMPTED  # NOT terminal
    assert cp.restart_count == 3
    assert fx.client.deleted("JobSet") == []


async def test_jobset_delete_cascades_to_children():
    """Background-propagation parity in the fake: deleting the JobSet GCs
    child Jobs and their pods (the supervisor relies on this to not re-fire
    on orphaned children)."""
    fx = JobSetFixture()
    rid = str(uuid.uuid4())
    await fx.launch(rid)
    await fx.client.delete_object("JobSet", NS, rid)
    await asyncio.sleep(0)  # let call_soon GC run
    jobs, _ = await fx.client.list_objects("Job", NS)
    pods, _ = await fx.client.list_objects("Pod", NS)
    assert jobs == [] and pods == []
