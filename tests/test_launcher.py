"""Launcher tests: manifest composition contract + launch/cancel flow against
the fake kube client."""

import uuid

import pytest

from tpu_nexus.checkpoint.models import (
    JOB_LABEL_ALGORITHM_RUN,
    JOB_TEMPLATE_NAME_KEY,
    NEXUS_COMPONENT_LABEL,
    LifecycleStage,
)
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
from tpu_nexus.k8s.fake import FakeKubeClient
from tpu_nexus.launcher import (
    Launcher,
    LaunchSpec,
    compose_job,
    compose_jobset,
    coordinator_address,
)
from tpu_nexus.parallel.distributed import ENV_COORDINATOR, ENV_NUM_PROCESSES


def spec(**over):
    base = dict(
        run_id=str(uuid.uuid4()),
        algorithm="llama-pretrain",
        image="ghcr.io/x/workload:1",
        command=["python", "-m", "tpu_nexus.workload"],
        num_hosts=4,
        resources={"google.com/tpu": "4"},
        node_selector={"cloud.google.com/gke-tpu-topology": "4x4"},
        namespace="nexus",
    )
    base.update(over)
    return LaunchSpec(**base)


class TestManifests:
    def test_job_carries_supervisor_contract(self):
        s = spec(num_hosts=1)
        job = compose_job(s)
        # name IS the run id; labels are what the supervisor filters on
        assert job["metadata"]["name"] == s.run_id
        labels = job["metadata"]["labels"]
        assert labels[NEXUS_COMPONENT_LABEL] == JOB_LABEL_ALGORITHM_RUN
        assert labels[JOB_TEMPLATE_NAME_KEY] == s.algorithm
        assert job["spec"]["template"]["metadata"]["labels"][NEXUS_COMPONENT_LABEL]
        # OOM/fatal exit codes surface as PodFailurePolicy (FATAL path parity)
        codes = job["spec"]["podFailurePolicy"]["rules"][0]["onExitCodes"]["values"]
        assert codes == [137, 255]

    def test_multi_host_env_contract(self):
        s = spec(num_hosts=4)
        job = compose_job(s)
        env_list = job["spec"]["template"]["spec"]["containers"][0]["env"]
        env = {e["name"]: e.get("value") for e in env_list}
        assert env[ENV_NUM_PROCESSES] == "4"
        assert env[ENV_COORDINATOR] == coordinator_address(s, jobset=False)
        # process id comes from the completion-index annotation via downward
        # API (a $(VAR) reference would never expand — controller env comes
        # after user env)
        pid = next(e for e in env_list if e["name"] == "NEXUS_PROCESS_ID")
        assert "job-completion-index" in pid["valueFrom"]["fieldRef"]["fieldPath"]
        assert job["spec"]["completionMode"] == "Indexed"
        assert job["spec"]["completions"] == 4
        # plain-Job path gets stable pod DNS via subdomain + headless service
        assert job["spec"]["template"]["spec"]["subdomain"] == s.run_id

    def test_user_env_passthrough_carries_mesh_request(self):
        """A launch can request a specific parallelism layout: spec.env
        entries (e.g. the NEXUS_MESH contract run_workload parses) land in
        the container env of both manifest flavors."""
        s = spec(num_hosts=2, env={"NEXUS_MESH": "fsdp=2,sp=2", "NEXUS_MODEL_PRESET": "nexus_1b"})
        for manifest, path in (
            (compose_job(s), lambda m: m["spec"]["template"]),
            (compose_jobset(s), lambda m: m["spec"]["replicatedJobs"][0]["template"]["spec"]["template"]),
        ):
            env = {
                e["name"]: e.get("value")
                for e in path(manifest)["spec"]["containers"][0]["env"]
            }
            assert env["NEXUS_MESH"] == "fsdp=2,sp=2"
            assert env["NEXUS_MODEL_PRESET"] == "nexus_1b"

    def test_jobset_coordinator_dns(self):
        s = spec(num_hosts=4)
        js = compose_jobset(s)
        tmpl = js["spec"]["replicatedJobs"][0]["template"]["spec"]["template"]
        env = {e["name"]: e.get("value") for e in tmpl["spec"]["containers"][0]["env"]}
        assert env[ENV_COORDINATOR] == coordinator_address(s, jobset=True)
        assert env[ENV_COORDINATOR].startswith(f"{s.run_id}-workers-0-0.")
        # JobSet manages its own headless service; no subdomain on the pod
        assert "subdomain" not in tmpl["spec"]

    def test_single_host_omits_coordinator(self):
        job = compose_job(spec(num_hosts=1))
        env = {e["name"] for e in job["spec"]["template"]["spec"]["containers"][0]["env"]}
        assert ENV_COORDINATOR not in env

    def test_jobset_topology(self):
        s = spec()
        js = compose_jobset(s)
        assert js["kind"] == "JobSet"
        assert js["metadata"]["name"] == s.run_id
        assert js["spec"]["replicatedJobs"][0]["template"]["spec"]["completions"] == 4
        assert js["spec"]["failurePolicy"]["maxRestarts"] == 3

    def test_tpu_resources_and_selector(self):
        pod = compose_job(spec())["spec"]["template"]["spec"]
        assert pod["containers"][0]["resources"]["limits"]["google.com/tpu"] == "4"
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "4x4"


class TestLauncher:
    async def test_launch_seeds_ledger_then_creates(self):
        store = InMemoryCheckpointStore()
        kube = FakeKubeClient()
        s = spec(num_hosts=1)
        cp = await Launcher(kube, store).launch(s, payload_uri="s3://payloads/x")
        assert cp.lifecycle_stage == LifecycleStage.BUFFERED
        assert cp.payload_uri == "s3://payloads/x"
        jobs, _ = await kube.list_objects("Job", "nexus")
        assert [j["metadata"]["name"] for j in jobs] == [s.run_id]

    async def test_multi_host_uses_jobset(self):
        store = InMemoryCheckpointStore()
        kube = FakeKubeClient()
        s = spec(num_hosts=4)
        await Launcher(kube, store).launch(s)
        jobsets, _ = await kube.list_objects("JobSet", "nexus")
        assert len(jobsets) == 1

    async def test_multi_host_plain_job_creates_headless_service(self):
        store = InMemoryCheckpointStore()
        kube = FakeKubeClient()
        s = spec(num_hosts=4)
        await Launcher(kube, store, use_jobset=False).launch(s)
        services, _ = await kube.list_objects("Service", "nexus")
        assert [sv["metadata"]["name"] for sv in services] == [s.run_id]
        assert services[0]["spec"]["clusterIP"] == "None"
        jobs, _ = await kube.list_objects("Job", "nexus")
        assert len(jobs) == 1

    async def test_cancel_guards_and_deletes(self):
        store = InMemoryCheckpointStore()
        kube = FakeKubeClient()
        s = spec(num_hosts=1)
        launcher = Launcher(kube, store)
        await launcher.launch(s)
        assert await launcher.cancel(s.algorithm, s.run_id, namespace="nexus")
        cp = store.read_checkpoint(s.algorithm, s.run_id)
        assert cp.lifecycle_stage == LifecycleStage.CANCELLED
        # second cancel is a guarded no-op
        assert not await launcher.cancel(s.algorithm, s.run_id, namespace="nexus")
