"""buildmeta + workload entrypoint env plumbing (coverage parity: the
reference gates every file at 70%, .testcoverage.yml:3-6 — no module may
stay dark)."""

import importlib
import os
from unittest import mock

import tpu_nexus


def test_buildmeta_defaults_to_package_version():
    from tpu_nexus.core import buildmeta

    assert buildmeta.APP_VERSION == tpu_nexus.__version__
    assert buildmeta.BUILD_NUMBER == "dev"


def test_buildmeta_env_injection():
    from tpu_nexus.core import buildmeta

    with mock.patch.dict(os.environ, {
        "TPU_NEXUS_APP_VERSION": "9.9.9", "TPU_NEXUS_BUILD_NUMBER": "b42",
    }):
        importlib.reload(buildmeta)
        assert buildmeta.APP_VERSION == "9.9.9"
        assert buildmeta.BUILD_NUMBER == "b42"
    importlib.reload(buildmeta)  # restore for other tests


def test_apply_platform_env_is_noop_without_request():
    from tpu_nexus.workload.__main__ import _apply_platform_env

    with mock.patch.dict(os.environ, {}, clear=False):
        os.environ.pop("JAX_PLATFORMS", None)
        _apply_platform_env()  # must not import jax or raise


def test_apply_platform_env_applies_cpu_mesh():
    """The env contract (JAX_PLATFORMS=cpu + device-count flag) must reach
    jax.config even on hosts whose TPU plugin pins the platform first."""
    import jax

    from tpu_nexus.workload.__main__ import _apply_platform_env

    # jax < 0.5 has no jax_num_cpu_devices option; there the device count
    # rides the XLA_FLAGS env var and only the platform pin is asserted
    has_num_cpu = hasattr(jax.config, "jax_num_cpu_devices")
    before_platforms = jax.config.jax_platforms
    before_n = jax.config.jax_num_cpu_devices if has_num_cpu else None
    try:
        with mock.patch.dict(os.environ, {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }):
            _apply_platform_env()
            assert jax.config.jax_platforms == "cpu"
            if has_num_cpu:
                assert jax.config.jax_num_cpu_devices == 8
    finally:
        jax.config.update("jax_platforms", before_platforms)
        if has_num_cpu:
            jax.config.update("jax_num_cpu_devices", before_n)
