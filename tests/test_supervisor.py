"""End-to-end supervision scenarios.

Port of the reference's integration-in-miniature suite
(services/supervisor_test.go:542-580; SURVEY.md §3.4/§4): fake k8s client
seeded with Events/Pods/Jobs replayed through real informers, in-memory
ledger seeded with one row per scenario, full service loop, then assert the
resulting lifecycle stage.  Poll-with-deadline (actor idle()) replaces the
reference's fixed sleeps.

Scenarios 1-7 are the reference matrix + the CANCELLED guard; the TPU
scenarios exercise the extended taxonomy (compile abort, HBM OOM,
preemption, ICI) from BASELINE.json.
"""

import asyncio
import uuid

import pytest

from tpu_nexus.checkpoint.models import (
    JOB_LABEL_ALGORITHM_RUN,
    JOB_TEMPLATE_NAME_KEY,
    NEXUS_COMPONENT_LABEL,
    POD_JOB_NAME_LABEL,
    CheckpointedRequest,
    LifecycleStage,
)
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.k8s.fake import FakeKubeClient
from tpu_nexus.supervisor.service import ProcessingConfig, Supervisor
from tpu_nexus.supervisor.taxonomy import (
    MSG_DEADLINE_EXCEEDED,
    MSG_FATAL_ERROR,
    MSG_STUCK_IN_PENDING,
)
from datetime import timedelta

NS = "nexus"
ALGORITHM = "test-algorithm"


def run_labels():
    return {
        NEXUS_COMPONENT_LABEL: JOB_LABEL_ALGORITHM_RUN,
        JOB_TEMPLATE_NAME_KEY: ALGORITHM,
    }


def job_obj(request_id):
    return {
        "kind": "Job",
        "metadata": {
            "name": request_id,
            "namespace": NS,
            "uid": str(uuid.uuid4()),
            "labels": run_labels(),
        },
        "status": {},
    }


def jobset_obj(request_id, conditions=None):
    return {
        "kind": "JobSet",
        "metadata": {
            "name": request_id,
            "namespace": NS,
            "uid": str(uuid.uuid4()),
            "labels": run_labels(),
        },
        "status": {"conditions": conditions or []},
    }


def pod_obj(request_id, suffix="-pod-0", container_statuses=None):
    return {
        "kind": "Pod",
        "metadata": {
            "name": request_id + suffix,
            "namespace": NS,
            "uid": str(uuid.uuid4()),
            "labels": {POD_JOB_NAME_LABEL: request_id, **run_labels()},
        },
        "status": {"containerStatuses": container_statuses or []},
    }


def event_obj(reason, message, kind, obj_name):
    return {
        "kind": "Event",
        "metadata": {"name": f"evt-{reason}-{obj_name}", "namespace": NS},
        "reason": reason,
        "message": message,
        "type": "Warning",
        "involvedObject": {"kind": kind, "name": obj_name, "namespace": NS},
    }


def seed_checkpoint(store, request_id, stage=LifecycleStage.BUFFERED):
    store.upsert_checkpoint(
        CheckpointedRequest(algorithm=ALGORITHM, id=request_id, lifecycle_stage=stage)
    )


class Fixture:
    """newFixture parity (reference supervisor_test.go:31-44)."""

    def __init__(self, objects):
        self.store = InMemoryCheckpointStore()
        self.client = FakeKubeClient(objects)
        self.supervisor = Supervisor(
            self.client,
            self.store,
            NS,
            resync_period=timedelta(0),
        )
        self.supervisor.init(
            ProcessingConfig(
                failure_rate_base_delay=timedelta(milliseconds=5),
                failure_rate_max_delay=timedelta(milliseconds=50),
                rate_limit_elements_per_second=0,
                rate_limit_elements_burst=100,
                workers=4,
            )
        )
        self.ctx = LifecycleContext()

    async def run_until_idle(self, timeout=10.0):
        task = asyncio.create_task(self.supervisor.start(self.ctx))
        # let informers sync + events flow, then wait for the queues to drain
        await asyncio.sleep(0.05)
        assert await self.supervisor.idle(timeout=timeout)
        self.ctx.cancel()
        await task

    def stage_of(self, request_id):
        cp = self.store.read_checkpoint(ALGORITHM, request_id)
        return cp.lifecycle_stage if cp else None


# ---------------------------------------------------------------------------
# Reference scenario matrix (SURVEY §4): one fixture per scenario, pre-seeded
# ---------------------------------------------------------------------------


async def scenario(reason, kind_under_test, seed_stage, event_message="boom",
                   container_statuses=None, event_kind=None):
    rid = str(uuid.uuid4())
    job = job_obj(rid)
    pod = pod_obj(rid, container_statuses=container_statuses)
    target_name = rid if (event_kind or kind_under_test) == "Job" else pod["metadata"]["name"]
    objects = {
        "Job": [job],
        "Pod": [pod],
        "Event": [event_obj(reason, event_message, event_kind or kind_under_test, target_name)],
    }
    fx = Fixture(objects)
    seed_checkpoint(fx.store, rid, seed_stage)
    await fx.run_until_idle()
    return fx, rid


async def test_job_failed_create_to_scheduling_failed():
    fx, rid = await scenario("FailedCreate", "Job", LifecycleStage.BUFFERED)
    assert fx.stage_of(rid) == LifecycleStage.SCHEDULING_FAILED
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.algorithm_failure_cause == MSG_STUCK_IN_PENDING
    assert cp.algorithm_failure_details == "boom"
    assert rid in fx.client.deleted("Job")


async def test_job_deadline_exceeded():
    fx, rid = await scenario("DeadlineExceeded", "Job", LifecycleStage.RUNNING)
    assert fx.stage_of(rid) == LifecycleStage.DEADLINE_EXCEEDED
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.algorithm_failure_cause == MSG_DEADLINE_EXCEEDED
    assert rid in fx.client.deleted("Job")


async def test_job_backoff_limit_exceeded_to_deadline_exceeded():
    fx, rid = await scenario("BackoffLimitExceeded", "Job", LifecycleStage.RUNNING)
    assert fx.stage_of(rid) == LifecycleStage.DEADLINE_EXCEEDED
    assert rid in fx.client.deleted("Job")


async def test_job_pod_failure_policy_oom_to_failed():
    # exit 137 (OOM) surfaced via PodFailurePolicy (reference comments
    # services/supervisor.go:310-313)
    fx, rid = await scenario(
        "PodFailurePolicy",
        "Job",
        LifecycleStage.RUNNING,
        event_message="Container main for pod nexus/x failed with exit code 137 matching FailJob rule at index 0",
    )
    assert fx.stage_of(rid) == LifecycleStage.FAILED
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.algorithm_failure_cause == MSG_FATAL_ERROR
    assert "137" in cp.algorithm_failure_details
    assert rid in fx.client.deleted("Job")


async def test_pod_started_to_running():
    fx, rid = await scenario("Started", "Pod", LifecycleStage.BUFFERED, event_message="Started container")
    assert fx.stage_of(rid) == LifecycleStage.RUNNING
    assert fx.client.deleted("Job") == []  # no delete on ToRunning


async def test_pod_failed_maps_to_scheduling_failed_quirk():
    # quirk preserved: Pod Failed -> SCHEDULING_FAILED, not FAILED
    # (reference services/supervisor.go:234-243; supervisor_test.go:398-401)
    fx, rid = await scenario("Failed", "Pod", LifecycleStage.RUNNING)
    assert fx.stage_of(rid) == LifecycleStage.SCHEDULING_FAILED
    assert rid in fx.client.deleted("Job")


async def test_pod_backoff_to_failed():
    fx, rid = await scenario("BackOff", "Pod", LifecycleStage.RUNNING,
                             event_message="Back-off restarting failed container")
    assert fx.stage_of(rid) == LifecycleStage.FAILED
    assert rid in fx.client.deleted("Job")


async def test_pod_started_on_cancelled_checkpoint_is_noop():
    # the IsFinished guard: cancelled runs are protected from late Started
    # events (reference services/supervisor.go:275-279; CANCELLED fixture
    # supervisor_test.go:473-540)
    fx, rid = await scenario("Started", "Pod", LifecycleStage.CANCELLED)
    assert fx.stage_of(rid) == LifecycleStage.CANCELLED
    assert fx.client.deleted("Job") == []


async def test_unknown_job_reason_ignored():
    fx, rid = await scenario("SuccessfulCreate", "Job", LifecycleStage.BUFFERED)
    assert fx.stage_of(rid) == LifecycleStage.BUFFERED
    assert fx.supervisor.decisions_enqueued == 0


async def test_non_nexus_event_filtered():
    rid = str(uuid.uuid4())
    job = job_obj(rid)
    del job["metadata"]["labels"][NEXUS_COMPONENT_LABEL]  # not a nexus run
    objects = {"Job": [job], "Event": [event_obj("FailedCreate", "x", "Job", rid)]}
    fx = Fixture(objects)
    seed_checkpoint(fx.store, rid)
    await fx.run_until_idle()
    assert fx.stage_of(rid) == LifecycleStage.BUFFERED
    assert fx.supervisor.events_filtered >= 1


async def test_missing_checkpoint_deletes_job_and_retries():
    # reference :265-273: no metadata -> delete job anyway, return error
    rid = str(uuid.uuid4())
    objects = {"Job": [job_obj(rid)], "Event": [event_obj("FailedCreate", "x", "Job", rid)]}
    fx = Fixture(objects)  # store NOT seeded
    task = asyncio.create_task(fx.supervisor.start(fx.ctx))
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline and rid not in fx.client.deleted("Job"):
        await asyncio.sleep(0.01)
    assert rid in fx.client.deleted("Job")
    assert fx.store.read_checkpoint(ALGORITHM, rid) is None
    fx.ctx.cancel()
    await task


# ---------------------------------------------------------------------------
# TPU taxonomy scenarios (BASELINE.json failure classes)
# ---------------------------------------------------------------------------


async def test_pod_xla_compile_abort():
    statuses = [
        {
            "name": "main",
            "state": {
                "terminated": {
                    "exitCode": 1,
                    "reason": "Error",
                    "message": "jaxlib.xla_extension.XlaRuntimeError: INVALID_ARGUMENT: XLA compilation failed: HLO module has mismatched shapes",
                }
            },
        }
    ]
    fx, rid = await scenario(
        "Failed", "Pod", LifecycleStage.RUNNING,
        event_message="Pod failed", container_statuses=statuses,
    )
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.FAILED
    assert "compile" in cp.algorithm_failure_cause.lower()
    assert "XLA compilation failed" in cp.algorithm_failure_details
    assert rid in fx.client.deleted("Job")


async def test_pod_hbm_oom():
    statuses = [
        {
            "name": "main",
            "state": {
                "terminated": {
                    "exitCode": 137,
                    "reason": "Error",
                    "message": "RESOURCE_EXHAUSTED: Attempting to allocate 12.5G. That was not possible. There are 9.1G free. HBM exhausted on device 3",
                }
            },
        }
    ]
    fx, rid = await scenario(
        "Failed", "Pod", LifecycleStage.RUNNING,
        event_message="Pod failed", container_statuses=statuses,
    )
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.FAILED
    assert "HBM" in cp.algorithm_failure_cause
    assert rid in fx.client.deleted("Job")


async def test_pod_tpu_preemption_is_restartable():
    fx, rid = await scenario(
        "TPUPreempted", "Pod", LifecycleStage.RUNNING,
        event_message="TPU node was preempted by Cloud provider",
    )
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.PREEMPTED
    assert cp.restart_count == 1
    assert not cp.is_finished()  # restartable, NOT terminal
    assert fx.client.deleted("Job") == []  # restart-from-step: no delete


async def test_jobset_ici_link_down():
    rid = str(uuid.uuid4())
    jobset = jobset_obj(rid)
    objects = {
        "JobSet": [jobset],
        "Event": [
            event_obj(
                "FailedJobs",
                "worker-2 terminated: ICI link down on chip 5, interconnect failure detected",
                "JobSet",
                rid,
            )
        ],
    }
    fx = Fixture(objects)
    seed_checkpoint(fx.store, rid, LifecycleStage.RUNNING)
    await fx.run_until_idle()
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.FAILED
    assert "ICI" in cp.algorithm_failure_cause
    assert rid in fx.client.deleted("JobSet")


async def test_jobset_started_to_running():
    rid = str(uuid.uuid4())
    objects = {
        "JobSet": [jobset_obj(rid)],
        "Event": [event_obj("Started", "all replicated jobs started", "JobSet", rid)],
    }
    fx = Fixture(objects)
    seed_checkpoint(fx.store, rid, LifecycleStage.BUFFERED)
    await fx.run_until_idle()
    assert fx.stage_of(rid) == LifecycleStage.RUNNING


async def test_hlo_trace_ref_extracted():
    statuses = [
        {
            "name": "main",
            "state": {
                "terminated": {
                    "exitCode": 1,
                    "reason": "Error",
                    "message": "XLA compilation failed; HLO dumped to gs://nexus-traces/run-42/module_0001.hlo",
                }
            },
        }
    ]
    fx, rid = await scenario(
        "Failed", "Pod", LifecycleStage.RUNNING,
        event_message="Pod failed", container_statuses=statuses,
    )
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.hlo_trace_ref == "gs://nexus-traces/run-42/module_0001.hlo"


# ---------------------------------------------------------------------------
# Live injection: events arriving after startup (watch path, not just LIST)
# ---------------------------------------------------------------------------


async def test_live_injected_event_storm_all_processed():
    """16-host storm: many events for one run -> exactly one terminal
    transition (idempotent via the IsFinished guard), p50 well under 5s."""
    rid = str(uuid.uuid4())
    objects = {"Job": [job_obj(rid)], "Pod": [pod_obj(rid)]}
    fx = Fixture(objects)
    seed_checkpoint(fx.store, rid, LifecycleStage.RUNNING)
    task = asyncio.create_task(fx.supervisor.start(fx.ctx))
    await asyncio.sleep(0.05)
    # storm: 16 duplicate failure events (one per host) for the same run
    for i in range(16):
        evt = event_obj("DeadlineExceeded", f"host-{i} deadline", "Job", rid)
        evt["metadata"]["name"] = f"evt-{i}"
        fx.client.inject("ADDED", "Event", evt)
    assert await fx.supervisor.idle(timeout=10)
    fx.ctx.cancel()
    await task
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.DEADLINE_EXCEEDED
    # first writer wins; the other 15 hit the IsFinished guard
    assert fx.client.deleted("Job").count(rid) == 1
    assert fx.supervisor.commit_latencies, "latency metric must be recorded"
    p50 = sorted(fx.supervisor.commit_latencies)[len(fx.supervisor.commit_latencies) // 2]
    assert p50 < 5.0
    summary = fx.supervisor.latency_summary()
    assert summary["count"] == len(fx.supervisor.commit_latencies)
    assert summary["p50"] <= summary["p95"] <= summary["max"]


async def test_duplicate_preemption_events_count_once():
    """One preemption incident fans out to N hosts' events; restart_count
    must record ONE preemption (PREEMPTED -> PREEMPTED duplicates are
    suppressed — a genuine second preemption passes through RUNNING first)."""
    rid = str(uuid.uuid4())
    pod = pod_obj(rid)
    fx = Fixture({"Job": [job_obj(rid)], "Pod": [pod]})
    seed_checkpoint(fx.store, rid, LifecycleStage.RUNNING)
    task = asyncio.create_task(fx.supervisor.start(fx.ctx))
    await asyncio.sleep(0.05)
    for host in range(8):
        evt = event_obj("TPUPreempted", f"host-{host} preempted", "Pod", pod["metadata"]["name"])
        evt["metadata"]["name"] = f"evt-preempt-{host}"
        fx.client.inject("ADDED", "Event", evt)
    assert await fx.supervisor.idle(timeout=10)
    fx.ctx.cancel()
    await task
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.PREEMPTED
    assert cp.restart_count == 1
    assert not fx.client.deleted("Job")


async def test_second_preemption_outside_window_counts_again():
    """A preemption landing on a PREEMPTED run with a STALE ledger write is
    a new incident (the replacement pod was reclaimed before the workload
    ever heartbeated) — it must increment restart_count, not be suppressed."""
    from datetime import datetime, timezone

    rid = str(uuid.uuid4())
    pod = pod_obj(rid)
    fx = Fixture({"Job": [job_obj(rid)], "Pod": [pod]})
    cp = CheckpointedRequest(
        algorithm=ALGORITHM, id=rid, lifecycle_stage=LifecycleStage.PREEMPTED, restart_count=1
    )
    cp.last_modified = datetime(2026, 1, 1, tzinfo=timezone.utc)  # long ago
    fx.store.upsert_checkpoint(cp)
    task = asyncio.create_task(fx.supervisor.start(fx.ctx))
    await asyncio.sleep(0.05)
    fx.client.inject(
        "ADDED", "Event",
        event_obj("TPUPreempted", "reclaimed again", "Pod", pod["metadata"]["name"]),
    )
    assert await fx.supervisor.idle(timeout=10)
    fx.ctx.cancel()
    await task
    got = fx.store.read_checkpoint(ALGORITHM, rid)
    assert got.lifecycle_stage == LifecycleStage.PREEMPTED
    assert got.restart_count == 2


async def test_second_preemption_counts_despite_future_skewed_clock():
    """Dedup must not trust workload-written wall clocks (VERDICT r2 weak #4):
    a PREEMPTED row whose last_modified was written by a host with a clock
    skewed into the FUTURE still gets its genuine second preemption counted.
    The supervisor judges dedup only from its own monotonic record of
    preemptions it committed — here there is none, so this must count."""
    from datetime import datetime, timedelta, timezone

    rid = str(uuid.uuid4())
    pod = pod_obj(rid)
    fx = Fixture({"Job": [job_obj(rid)], "Pod": [pod]})
    cp = CheckpointedRequest(
        algorithm=ALGORITHM, id=rid, lifecycle_stage=LifecycleStage.PREEMPTED, restart_count=1
    )
    # a skewed host stamped this ledger write 10 minutes in the future; the
    # old wall-clock dedup would read age < window and suppress forever
    cp.last_modified = datetime.now(timezone.utc) + timedelta(minutes=10)
    fx.store.upsert_checkpoint(cp)
    task = asyncio.create_task(fx.supervisor.start(fx.ctx))
    await asyncio.sleep(0.05)
    fx.client.inject(
        "ADDED", "Event",
        event_obj("TPUPreempted", "reclaimed again", "Pod", pod["metadata"]["name"]),
    )
    assert await fx.supervisor.idle(timeout=10)
    fx.ctx.cancel()
    await task
    got = fx.store.read_checkpoint(ALGORITHM, rid)
    assert got.restart_count == 2
    # refcounted per-run lock entries fully evict once drained
    assert fx.supervisor._run_locks == {}


async def test_latency_percentile_gauges_exported():
    """Every 16th executed decision exports p50/p95 gauges to the metrics
    plane (VERDICT r1 weak #8: the north-star number must not live only in an
    in-process deque)."""
    from tpu_nexus.core.telemetry import RecordingMetrics

    metrics = RecordingMetrics()
    rids = [str(uuid.uuid4()) for _ in range(16)]
    objects = {"Job": [job_obj(rid) for rid in rids]}
    store = InMemoryCheckpointStore()
    client = FakeKubeClient(objects)
    supervisor = Supervisor(client, store, NS, metrics=metrics, resync_period=timedelta(0))
    supervisor.init(
        ProcessingConfig(
            failure_rate_base_delay=timedelta(milliseconds=5),
            failure_rate_max_delay=timedelta(milliseconds=50),
            rate_limit_elements_per_second=0,
            workers=4,
        )
    )
    for rid in rids:
        seed_checkpoint(store, rid, LifecycleStage.RUNNING)
    ctx = LifecycleContext()
    task = asyncio.create_task(supervisor.start(ctx))
    await asyncio.sleep(0.05)
    for rid in rids:  # 16 distinct runs -> 16 EXECUTED decisions
        client.inject("ADDED", "Event", event_obj("DeadlineExceeded", "deadline", "Job", rid))
    assert await supervisor.idle(timeout=10)
    ctx.cancel()
    await task
    assert supervisor.decisions_executed == 16
    assert "detect_to_commit_p50_seconds" in metrics.gauges
    assert "detect_to_commit_p95_seconds" in metrics.gauges
    assert metrics.gauges["detect_to_commit_p50_seconds"] < 5.0


async def test_pod_failure_reenriched_from_fresh_cache():
    """Failed event classified BEFORE the pod cache sees the terminated
    container status: the executor must re-enrich from the freshest cached
    pod state and upgrade to the TPU decision (race found by live drive)."""
    rid = str(uuid.uuid4())
    pod = pod_obj(rid)
    objects = {"Job": [job_obj(rid)], "Pod": [pod]}
    fx = Fixture(objects)
    seed_checkpoint(fx.store, rid, LifecycleStage.RUNNING)
    task = asyncio.create_task(fx.supervisor.start(fx.ctx))
    await asyncio.sleep(0.05)
    # inject the event FIRST (cache still has no termination info)...
    fx.client.inject("ADDED", "Event", event_obj("Failed", "Pod failed", "Pod", pod["metadata"]["name"]))
    # ...then the pod status update lands
    pod["status"] = {"containerStatuses": [{"name": "main", "state": {"terminated": {
        "exitCode": 1, "reason": "Error",
        "message": "XLA compilation failed: unsupported dynamic shape"}}}]}
    fx.client.inject("MODIFIED", "Pod", pod)
    assert await fx.supervisor.idle(timeout=10)
    fx.ctx.cancel()
    await task
    cp = fx.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.FAILED
    assert "compile" in cp.algorithm_failure_cause.lower()
    assert "XLA compilation failed" in cp.algorithm_failure_details


async def test_pod_without_job_name_label_cannot_trigger_collection_delete():
    """A run-labeled pod missing its batch.kubernetes.io/job-name backlink
    classifies with request_id="" — that must be dropped at classification,
    and even if it slipped through, delete_object must refuse empty names
    (a DELETE at the collection URL is a namespace-wide deletecollection)."""
    rid = str(uuid.uuid4())
    pod = pod_obj(rid)
    del pod["metadata"]["labels"][POD_JOB_NAME_LABEL]
    objects = {
        "Pod": [pod],
        "Event": [event_obj("Failed", "boom", "Pod", pod["metadata"]["name"])],
    }
    fx = Fixture(objects)
    await fx.run_until_idle()
    # no delete of any kind happened — especially not an empty-name one
    assert not [a for a in fx.client.actions if a[0] == "delete"], fx.client.actions
    assert fx.store.read_checkpoint(ALGORITHM, rid) is None


async def test_delete_object_refuses_empty_name():
    from tpu_nexus.k8s.client import KubeClientError
    from tpu_nexus.k8s.rest import RestKubeClient

    fake = FakeKubeClient({})
    with pytest.raises(KubeClientError):
        await fake.delete_object("Job", NS, "")
    rest = RestKubeClient("https://127.0.0.1:1")  # guard fires before any I/O
    with pytest.raises(KubeClientError):
        await rest.delete_object("Job", NS, "")
