"""App composition tests (reference app/app_dependencies.go behavior:
nil-guarded singletons, store-type selection, fatal on unknown type,
end-to-end Start)."""

import asyncio
import uuid
from datetime import timedelta

import pytest

from tpu_nexus.app.config import SupervisorConfig
from tpu_nexus.app.dependencies import ApplicationServices
from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.k8s.fake import FakeKubeClient

from test_supervisor import ALGORITHM, NS, event_obj, job_obj, pod_obj


def test_unknown_store_type_fatal():
    cfg = SupervisorConfig(cql_store_type="bogus")
    services = ApplicationServices(fatal_exit=False)
    with pytest.raises(RuntimeError, match="unknown cql-store-type"):
        services.with_store_for(cfg)


def test_builder_is_idempotent_singleton():
    cfg = SupervisorConfig(cql_store_type="memory")
    services = ApplicationServices(fatal_exit=False).with_memory_store()
    first = services.store
    services.with_store_for(cfg)  # second build attempt must be a no-op
    services.with_memory_store()
    assert services.store is first


async def test_end_to_end_start_processes_event():
    rid = str(uuid.uuid4())
    client = FakeKubeClient(
        {
            "Job": [job_obj(rid)],
            "Pod": [pod_obj(rid)],
            "Event": [event_obj("FailedCreate", "no quota", "Job", rid)],
        }
    )
    cfg = SupervisorConfig(
        cql_store_type="memory",
        resource_namespace=NS,
        failure_rate_base_delay=timedelta(milliseconds=5),
        failure_rate_max_delay=timedelta(milliseconds=50),
        rate_limit_elements_per_second=0,
    )
    services = (
        ApplicationServices(fatal_exit=False)
        .with_store_for(cfg)
        .with_fake_kube_client(client)
        .with_supervisor(cfg, resync_period=timedelta(0))
    )
    services.store.upsert_checkpoint(
        CheckpointedRequest(algorithm=ALGORITHM, id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
    )
    ctx = LifecycleContext()
    task = asyncio.create_task(services.start(ctx, cfg))
    await asyncio.sleep(0.05)
    assert await services.supervisor.idle(timeout=10)
    ctx.cancel()
    await task
    cp = services.store.read_checkpoint(ALGORITHM, rid)
    assert cp.lifecycle_stage == LifecycleStage.SCHEDULING_FAILED
    assert rid in client.deleted("Job")
