"""Scale rehearsal: flagship-scale configs traced over production-shaped
meshes WITHOUT computing anything.

`jax.eval_shape` traces init and the full train step abstractly — no
device memory, no XLA compile — so divisibility and sharding-rule
consistency at 70B/8x7B scale (the configs a reference user would actually
bring) are validated in CI on the 8-device CPU image.  Sharding itself is
checked by building `NamedSharding`s for every param against big virtual
meshes: every rule-table lookup, axis-divisibility constraint, and
stage-sharding reshape runs exactly as it would on a v5e-256 pod.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpu_nexus.models import LlamaConfig, MoeConfig, adapter_for
from tpu_nexus.models.llama import param_count
from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, LOGICAL_RULES_FSDP_TP_PP
from tpu_nexus.parallel.mesh import AXIS_ORDER, MeshSpec
from tpu_nexus.parallel.sharding import sharding_tree
from tpu_nexus.workload.train import TrainConfig, make_optimizer


def _virtual_mesh(spec: MeshSpec, n_devices: int) -> Mesh:
    """A Mesh over abstract device placeholders — sufficient for building
    NamedShardings and checking axis divisibility, no real devices needed."""
    devs = np.asarray(jax.devices() * (n_devices // len(jax.devices())))
    return Mesh(devs.reshape(spec.resolve(n_devices)), AXIS_ORDER)


SCALE_CASES = [
    # (config, rule table, mesh spec, devices) — production-shaped layouts
    (LlamaConfig.llama3_8b(), LOGICAL_RULES_FSDP_TP, MeshSpec(fsdp=-1, tp=4), 32),
    (LlamaConfig.llama3_70b(), LOGICAL_RULES_FSDP_TP, MeshSpec(fsdp=-1, sp=4, tp=8), 256),
    (LlamaConfig.llama3_70b(), LOGICAL_RULES_FSDP_TP_PP, MeshSpec(pp=8, fsdp=-1, tp=8), 256),
    (MoeConfig.mixtral_8x7b(), LOGICAL_RULES_FSDP_TP, MeshSpec(fsdp=-1, ep=8, tp=4), 256),
]


class TestScaleRehearsal:
    @pytest.mark.parametrize(
        "cfg,rules,spec,n", SCALE_CASES,
        ids=["8b-fsdp-tp", "70b-fsdp-sp-tp", "70b-pp8-fsdp-tp", "mixtral-ep8-tp"],
    )
    def test_param_shardings_build_and_divide(self, cfg, rules, spec, n):
        """Every parameter gets a NamedSharding whose sharded dims divide
        evenly — the exact check GSPMD enforces at compile time on the pod."""
        adapter = adapter_for(cfg)
        mesh = _virtual_mesh(spec, n)
        shapes = jax.eval_shape(adapter.init, jax.random.PRNGKey(0))
        shardings = sharding_tree(adapter.axes(), mesh, rules)

        def check(shape_struct, sharding):
            spec_ = sharding.spec
            for dim, axes in zip(shape_struct.shape, list(spec_) + [None] * 99):
                if axes is None:
                    continue
                axes = (axes,) if isinstance(axes, str) else axes
                extent = math.prod(mesh.shape[a] for a in axes)
                assert dim % extent == 0, (
                    f"dim {dim} not divisible by mesh extent {extent} ({axes})"
                )

        jax.tree.map(check, shapes, shardings)

    @pytest.mark.parametrize(
        "cfg,rules,spec,n", SCALE_CASES,
        ids=["8b-fsdp-tp", "70b-fsdp-sp-tp", "70b-pp8-fsdp-tp", "mixtral-ep8-tp"],
    )
    def test_train_step_traces_at_scale(self, cfg, rules, spec, n):
        """Abstractly trace ONE full train step (loss + grads + adam) at
        flagship scale over the virtual mesh: catches shape/divisibility
        bugs (microbatching, stage reshapes, chunked CE) with zero FLOPs."""
        adapter = adapter_for(cfg)
        mesh = _virtual_mesh(spec, n)
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        optimizer = make_optimizer(tcfg)
        loss_fn = adapter.make_loss(tcfg, mesh, rules=rules)
        # global batch: a sane per-chip batch times the data extent
        batch = 2 * mesh.shape["dp"] * mesh.shape["fsdp"] * max(1, mesh.shape["pp"])
        seq = 512 * max(1, mesh.shape["sp"])
        tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32)

        def step(params, tokens):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, tokens
            )
            opt_state = optimizer.init(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return loss, metrics

        params_shape = jax.eval_shape(adapter.init, jax.random.PRNGKey(0))
        with mesh:
            out = jax.eval_shape(step, params_shape, tokens)
        loss_shape = out[0]
        assert loss_shape.shape == () and loss_shape.dtype == jnp.float32

    def test_70b_param_count_sanity(self):
        assert 69e9 < param_count(LlamaConfig.llama3_70b()) < 72e9
