"""Checkpoint durability chaos (ISSUE 5): torn saves, bit rot, preemption
mid-save — and the recovery the restart-from-step contract promises.

The drills assert the four durability invariants end to end:

* a crash between the payload write and the commit marker
  (``ckpt-crash-mid-save``) costs at most the uncommitted step: the restart
  resumes from the last *committed* step with a loss trajectory bit-identical
  to an uninterrupted run, and the torn directory is quarantined;
* the ledger NEVER points at an uncommitted or corrupt URI — the publish
  sits behind the durability barrier, so an injected commit failure leaves
  the previous pointer in place;
* a corrupted committed leaf (``ckpt-bitflip``) rolls the next restore back
  exactly one step, cause recorded to metrics and the ledger;
* SIGTERM converts to a saved step: the emergency save beats the grace
  budget (and skips the duplicate when the preemption landed inside a save
  window whose commit completed), and the row exits PREEMPTED with the
  saved step in the details.

Quick tier runs in tier-1; the full seed-matrix corruption fuzz and the
every-boundary crash drill ride behind the ``slow`` marker.  Model is the
mnist MLP throughout — the durability layer is model-agnostic and the tiny
jit keeps the drills inside the tier-1 wall-clock budget.
"""

import json
import os
import subprocess
import sys
import uuid

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.checkpoint.store import InMemoryCheckpointStore, SqliteCheckpointStore
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.core.telemetry import RecordingMetrics
from tpu_nexus.models.registry import get_adapter
from tpu_nexus.parallel import MeshSpec
from tpu_nexus.parallel.distributed import ProcessContext
from tpu_nexus.workload import durability
from tpu_nexus.workload.faults import (
    FaultPlan,
    _flip_committed_leaf,
    checkpoint_fault_hook,
    maybe_inject,
)
from tpu_nexus.workload.harness import WorkloadConfig, run_workload
from tpu_nexus.workload.tensor_checkpoint import (
    CheckpointCorrupt,
    CheckpointMissing,
    CheckpointUncommitted,
    TensorCheckpointer,
)

ALGORITHM = "mnist-train"
CTX = ProcessContext(
    run_id="run-ckpt", algorithm=ALGORITHM, process_id=0, num_processes=1, coordinator=None
)


def mnist_cfg(**over):
    from tpu_nexus.workload.health import HealthConfig

    base = dict(
        model=get_adapter("mnist"),
        mesh=MeshSpec(fsdp=-1),
        batch_size=8,
        seq_len=16,
        steps=6,
        heartbeat_every=2,
        checkpoint_every=2,
        # sentinel off: these drills pin seed-calibrated bit-identical loss
        # trajectories, and the gating ops cost compile time in every one
        # of this file's ~20 fresh jits (tier-1 870s budget).  The
        # health x checkpoint composition has its own drills:
        # tests/test_training_health.py rolls back against REAL commits.
        health=HealthConfig(enabled=False),
    )
    base.update(over)
    return WorkloadConfig(**base)


def seeded_store(rid=CTX.run_id, algorithm=ALGORITHM):
    store = InMemoryCheckpointStore()
    store.upsert_checkpoint(
        CheckpointedRequest(algorithm=algorithm, id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
    )
    return store


def tiny_state(step, scale=1.0):
    return {
        "params": {"w": jnp.arange(8.0) * scale, "b": jnp.ones((3,)) * step},
        "step": jnp.int32(step),
    }


def committed_steps(directory, *steps):
    tc = TensorCheckpointer(directory)
    for s in steps:
        tc.save(s, tiny_state(s))
        tc.commit(s)
    tc.close()


# -- the durability layer itself -----------------------------------------------


class TestDurabilityLayer:
    def test_commit_then_verify_roundtrip(self, tmp_path):
        d = str(tmp_path)
        tc = TensorCheckpointer(d)
        tc.save(2, tiny_state(2))
        uri = tc.commit(2)
        assert uri == f"{d}/2" and tc.last_committed_step == 2
        manifest = tc.verify(2)
        assert manifest["step"] == 2 and manifest["file_count"] > 0
        assert os.path.isfile(os.path.join(d, "2", durability.MANIFEST_NAME))
        restored = tc.restore(tiny_state(0))
        np.testing.assert_array_equal(restored["params"]["w"], np.arange(8.0))
        assert int(restored["step"]) == 2
        tc.close()

    def test_restore_empty_directory_classified_missing(self, tmp_path):
        tc = TensorCheckpointer(str(tmp_path / "fresh"))
        with pytest.raises(CheckpointMissing) as exc:
            tc.restore_params()
        assert exc.value.cause == "missing"
        # back-compat: pre-durability callers caught FileNotFoundError
        assert isinstance(exc.value, FileNotFoundError)
        tc.close()

    def test_restore_uncommitted_step_classified(self, tmp_path):
        """Step dir present but no commit marker: a torn save, distinct from
        both absence and corruption."""
        d = str(tmp_path)
        committed_steps(d, 2)
        tc = TensorCheckpointer(d)
        tc.save(4, tiny_state(4))
        tc.wait()  # payload durable — but never committed
        tc.close()
        fresh = TensorCheckpointer(d)
        with pytest.raises(CheckpointUncommitted) as exc:
            fresh.restore_params(4)  # explicit step: the caller demanded it
        assert exc.value.cause == "uncommitted"
        # no step: rollback lands the previous committed step
        params = fresh.restore_params()
        np.testing.assert_array_equal(params["w"], np.arange(8.0))
        assert fresh.rollbacks[0]["step"] == 4
        assert fresh.rollbacks[0]["cause"] == "uncommitted"
        fresh.close()

    def test_restore_checksum_mismatch_classified(self, tmp_path):
        d = str(tmp_path)
        committed_steps(d, 2, 4)
        _flip_committed_leaf(os.path.join(d, "4"))
        tc = TensorCheckpointer(d)
        with pytest.raises(CheckpointCorrupt) as exc:
            tc.restore_params(4)
        assert exc.value.cause == "corrupt" and "checksum mismatch" in str(exc.value)
        tc.close()

    def test_corrupt_latest_rolls_back_one_step_and_quarantines(self, tmp_path):
        d = str(tmp_path)
        committed_steps(d, 2, 4)
        _flip_committed_leaf(os.path.join(d, "4"))
        tc = TensorCheckpointer(d)
        assert tc.latest_verified_step() == 2
        assert [e["cause"] for e in tc.rollbacks] == ["corrupt"]
        assert tc.rollbacks[0]["quarantined_to"].endswith("4" + durability.QUARANTINE_SUFFIX)
        # the bad directory is out of the step scan but kept for postmortems
        assert sorted(n for n in os.listdir(d) if not n.startswith(".")) == [
            "2",
            "4" + durability.QUARANTINE_SUFFIX,
        ]
        restored = tc.restore(tiny_state(0))
        assert int(restored["step"]) == 2
        tc.close()

    def test_read_only_rollback_leaves_directories(self, tmp_path):
        """Serving restores with quarantine=False: skip, don't mutate."""
        d = str(tmp_path)
        committed_steps(d, 2, 4)
        os.remove(os.path.join(d, "4", durability.MANIFEST_NAME))
        tc = TensorCheckpointer(d)
        assert tc.latest_verified_step(quarantine=False) == 2
        assert tc.rollbacks[0]["cause"] == "uncommitted"
        assert "quarantined_to" not in tc.rollbacks[0]
        assert os.path.isdir(os.path.join(d, "4"))
        tc.close()

    def test_manifest_detects_missing_and_truncated_files(self, tmp_path):
        d = str(tmp_path)
        committed_steps(d, 2)
        step_dir = os.path.join(d, "2")
        victim = os.path.join(step_dir, sorted(durability.manifest_files(step_dir))[0])
        original = open(victim, "rb").read()
        with open(victim, "wb") as fh:
            fh.write(original[: max(len(original) // 2, 1)])
        with pytest.raises(CheckpointCorrupt, match="bytes"):
            durability.verify_step(step_dir, 2)
        os.remove(victim)
        with pytest.raises(CheckpointCorrupt, match="missing"):
            durability.verify_step(step_dir, 2)

    def test_adopt_unmanifested_legacy_steps(self, tmp_path):
        """Upgrade migration (docs/CHECKPOINTS.md): pre-durability steps
        carry no manifest and would ALL quarantine as torn saves on the
        first post-upgrade restart; explicit adoption commits a manifest
        from the bytes on disk, and the verifier accepts them from then
        on."""
        d = str(tmp_path)
        tc = TensorCheckpointer(d)
        for s in (2, 4):
            tc.save(s, tiny_state(s))
        tc.wait()
        tc.close()  # legacy shape: orbax-finalized, never commit()ed
        assert durability.adopt_unmanifested_steps(d) == [2, 4]
        assert durability.adopt_unmanifested_steps(d) == []  # idempotent
        fresh = TensorCheckpointer(d)
        assert fresh.latest_verified_step() == 4 and fresh.rollbacks == []
        params = fresh.restore_params()
        np.testing.assert_array_equal(params["w"], np.arange(8.0))
        fresh.close()

    def test_scan_tolerates_step_vanishing_mid_walk(self, tmp_path, monkeypatch):
        """Multi-host race: another host's quarantine rename can delete a
        step directory between this host's list_steps and its verify_step.
        The scan must record the miss and keep walking, not crash — a
        non-coordinator dying here wedges the whole collective restore."""
        d = str(tmp_path)
        committed_steps(d, 2, 4)
        real_verify = durability.verify_step

        def racing_verify(step_dir, step=None):
            if step == 4:  # simulate the rename landing mid-walk
                raise durability.CheckpointMissing(f"{step_dir} vanished")
            return real_verify(step_dir, step)

        monkeypatch.setattr(durability, "verify_step", racing_verify)
        step, rollbacks = durability.newest_verified_step(d, quarantine=True)
        assert step == 2
        assert [r["cause"] for r in rollbacks] == ["missing"]
        # nothing to quarantine — the other host already renamed it
        assert "quarantined_to" not in rollbacks[0]
        assert sorted(os.listdir(d)) == ["2", "4"]

    def test_durability_import_stays_stdlib_only(self):
        """The supervisor wires durability.resolve_verified_uri into the
        watchdog (service.init) — importing it must not drag jax/orbax into
        a process that never trains (workload/__init__ is lazy, PEP 562)."""
        probe = (
            "import sys\n"
            "import tpu_nexus.workload.durability\n"
            "assert 'jax' not in sys.modules, 'jax leaked'\n"
            "assert 'orbax' not in sys.modules, 'orbax leaked'\n"
            "from tpu_nexus.workload import WorkloadConfig\n"  # lazy export still works
        )
        subprocess.run([sys.executable, "-c", probe], check=True, timeout=60)

    def test_wrong_shaped_manifest_classifies_corrupt(self, tmp_path):
        """A manifest that parses as JSON but is wrong-shaped (files as a
        list, a file entry as a string, the whole document a list) is
        corruption like any other — it must classify, never escape as a
        raw TypeError/AttributeError the rollback scan can't catch."""
        d = str(tmp_path)
        committed_steps(d, 2)
        marker = os.path.join(d, "2", durability.MANIFEST_NAME)
        for bad in (
            '{"step": 2, "files": []}',
            '{"step": 2, "files": {"a": "junk"}}',
            '{"step": 2, "files": {"a": {"bytes": "3.5", "sha256": "x"}}}',
            "[1, 2]",
        ):
            with open(marker, "w", encoding="utf-8") as fh:
                fh.write(bad)
            with pytest.raises(CheckpointCorrupt, match="unreadable manifest"):
                durability.verify_step(os.path.join(d, "2"), 2)
        # and the rollback scan records it instead of crashing
        step, rollbacks = durability.newest_verified_step(d, quarantine=False)
        assert step is None
        assert [r["cause"] for r in rollbacks] == ["corrupt"]

    def test_verify_classifies_raw_oserror_as_checkpoint_error(
        self, tmp_path, monkeypatch
    ):
        """A stat/read that fails RAW mid-verification (the quarantine
        rename landing between the file checks, or an I/O error) must come
        out classified — the rollback scan and the watchdog resolver catch
        only CheckpointError, and a leaked FileNotFoundError would crash
        the very scan built to tolerate the race."""
        d = str(tmp_path)
        committed_steps(d, 2, 4)
        real = durability._sha256_file

        def renaming_hash(path, chunk=1 << 20):
            if os.sep + "4" + os.sep in path and os.path.isdir(os.path.join(d, "4")):
                os.rename(os.path.join(d, "4"), os.path.join(d, "4.gone"))
            return real(path, chunk)  # raises raw FileNotFoundError for step 4

        monkeypatch.setattr(durability, "_sha256_file", renaming_hash)
        step, rollbacks = durability.newest_verified_step(d, quarantine=False)
        assert step == 2
        assert [r["cause"] for r in rollbacks] == ["missing"]

    def test_verify_classifies_unreadable_file_as_corrupt(
        self, tmp_path, monkeypatch
    ):
        """An I/O error on a file whose directory is still present is
        corruption, not absence."""
        d = str(tmp_path)
        committed_steps(d, 2)

        def failing_hash(path, chunk=1 << 20):
            raise OSError("injected I/O error")

        monkeypatch.setattr(durability, "_sha256_file", failing_hash)
        with pytest.raises(CheckpointCorrupt, match="unreadable"):
            durability.verify_step(os.path.join(d, "2"), 2)

    def test_caching_resolver_skips_rehash_when_marker_unchanged(
        self, tmp_path, monkeypatch
    ):
        """The watchdog sweep re-checks every PREEMPTED row every interval;
        the supervisor wires CachingUriResolver so a verified URI costs one
        stat per sweep, not a full re-hash of the checkpoint."""
        d = str(tmp_path)
        committed_steps(d, 2, 4)
        calls = {"n": 0}
        real = durability._sha256_file

        def counting_hash(path, chunk=1 << 20):
            calls["n"] += 1
            return real(path, chunk)

        monkeypatch.setattr(durability, "_sha256_file", counting_hash)
        resolver = durability.CachingUriResolver()
        uri = f"{d}/4"
        assert resolver(uri) == uri
        first = calls["n"]
        assert first > 0
        assert resolver(uri) == uri
        assert calls["n"] == first  # cache hit: marker stat only
        # marker identity change invalidates the cache entry
        marker = os.path.join(d, "4", durability.MANIFEST_NAME)
        os.utime(marker, ns=(1, 1))
        assert resolver(uri) == uri
        assert calls["n"] > first

    def test_caching_resolver_sees_later_commits(self, tmp_path):
        d = str(tmp_path)
        resolver = durability.CachingUriResolver()
        assert resolver(f"{d}/4") is None  # nothing committed yet
        committed_steps(d, 4)
        assert resolver(f"{d}/4") == f"{d}/4"  # the later commit is seen

    def test_caching_resolver_caches_negative_until_directory_changes(
        self, tmp_path, monkeypatch
    ):
        """A parked row whose directory never verifies must not pay a full
        re-hash of every step on every sweep — the negative verdict is
        cached against the directory fingerprint, and any commit (or
        adoption/quarantine) invalidates it."""
        d = str(tmp_path)
        committed_steps(d, 4)
        _flip_committed_leaf(os.path.join(d, "4"))
        calls = {"n": 0}
        real = durability._sha256_file

        def counting_hash(path, chunk=1 << 20):
            calls["n"] += 1
            return real(path, chunk)

        monkeypatch.setattr(durability, "_sha256_file", counting_hash)
        resolver = durability.CachingUriResolver()
        assert resolver(f"{d}/4") is None  # only step is corrupt
        first = calls["n"]
        assert first > 0
        assert resolver(f"{d}/4") is None
        assert calls["n"] == first  # negative cached: listdir + stats only
        committed_steps(d, 6)  # the directory changed — must be re-scanned
        assert resolver(f"{d}/4") == f"{d}/6"

    def test_resolver_maps_bad_uri_to_previous_verified(self, tmp_path):
        d = str(tmp_path)
        committed_steps(d, 2, 4)
        assert durability.resolve_verified_uri(f"{d}/4") == f"{d}/4"
        _flip_committed_leaf(os.path.join(d, "4"))
        assert durability.resolve_verified_uri(f"{d}/4") == f"{d}/2"
        assert durability.resolve_verified_uri("not-a-step-uri") is None
        assert durability.resolve_verified_uri(f"{tmp_path}/none/9") is None
        # resolver never quarantines (the watchdog is read-only)
        assert os.path.isdir(os.path.join(d, "4"))


# -- fault-plan plumbing -------------------------------------------------------


def test_maybe_inject_guards_vacuous_checkpoint_drills():
    plan = FaultPlan(mode="ckpt-bitflip", step=3)
    # loop without a checkpointer: the drill would inject nothing — raise
    with pytest.raises(ValueError, match="no checkpointer"):
        maybe_inject(plan, 3)
    # checkpointer wired: the hook owns the fault, the loop stays silent
    maybe_inject(plan, 3, checkpoint_faults_handled=True)
    # off-step: silent either way
    maybe_inject(plan, 2)


def test_checkpoint_fault_hook_only_for_checkpoint_modes():
    assert checkpoint_fault_hook(FaultPlan(mode=None, step=0)) is None
    assert checkpoint_fault_hook(FaultPlan(mode="hbm-oom", step=0)) is None
    assert checkpoint_fault_hook(FaultPlan(mode="ckpt-bitflip", step=2)) is not None


def test_vacuous_checkpoint_drill_fails_loudly(tmp_path, monkeypatch):
    """A checkpoint fault whose NEXUS_FAULT_STEP is never a commit boundary
    fires nothing — the run must raise, not exit 0 looking like a passed
    drill (the checkpointer being wired silences maybe_inject, so the
    harness itself has to check the hook actually fired)."""
    monkeypatch.setenv("NEXUS_FAULT_MODE", "ckpt-bitflip")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "3")  # boundaries are 2, 4, 6
    with pytest.raises(RuntimeError, match="injected nothing"):
        run_workload(
            mnist_cfg(checkpoint_dir=str(tmp_path)), store=seeded_store(),
            ctx=CTX, lifecycle=LifecycleContext(),
        )


# -- harness: publish-after-durability -----------------------------------------


def test_commit_failure_never_reaches_ledger(tmp_path, monkeypatch):
    """ISSUE 5 satellite (harness publish-before-durability regression): an
    injected failed async save must never reach the ledger — the pointer
    stays on the last step whose barrier completed."""
    original = TensorCheckpointer.commit

    def failing_commit(self, step):
        if step == 4:
            raise RuntimeError("injected async save failure at the barrier")
        return original(self, step)

    monkeypatch.setattr(TensorCheckpointer, "commit", failing_commit)
    store = seeded_store()
    with pytest.raises(RuntimeError, match="injected async save failure"):
        run_workload(
            mnist_cfg(checkpoint_dir=str(tmp_path)), store=store, ctx=CTX,
            lifecycle=LifecycleContext(),
        )
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.tensor_checkpoint_uri == f"{tmp_path}/2"
    assert row.lifecycle_stage == LifecycleStage.RUNNING  # crash: supervisor's call
    # the torn step is on disk but a fresh restore rolls back to 2
    tc = TensorCheckpointer(str(tmp_path))
    assert tc.latest_verified_step() == 2
    tc.close()


def _cancelling_data(lc, at, batch=8, seed=0):
    """The mnist stream, cancelling the lifecycle while producing batch
    ``at`` — an in-process preemption without real signals."""
    src = get_adapter("mnist").data(batch, 16, seed=seed)
    i = 0
    while True:
        if i == at:
            lc.cancel("SIGTERM")
        yield next(src)
        i += 1


def test_emergency_save_on_cancellation(tmp_path):
    """Preemption converts to a saved step: the loop stops, the emergency
    checkpoint commits inside the grace budget, and the row lands PREEMPTED
    with the saved step in the details."""
    d = str(tmp_path)
    store = seeded_store()
    lc = LifecycleContext()
    rec = RecordingMetrics()
    result = run_workload(
        # checkpoint_every=50: no periodic boundary fires — the emergency
        # save is the ONLY checkpoint this run cuts
        mnist_cfg(steps=10, checkpoint_every=50, checkpoint_dir=d),
        store=store, ctx=CTX, data=_cancelling_data(lc, 3), lifecycle=lc,
        telemetry=rec,
    )
    assert result["preempted"] is True
    step = result["emergency_step"]
    assert step == result["final_step"] and 0 < step < 10
    assert result["emergency_skipped"] is False
    # the emergency save beats the grace deadline
    assert result["emergency_save_s"] <= result["grace_s"]
    assert rec.counters["train.emergency_save"] == 1
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.PREEMPTED
    assert row.algorithm_failure_cause == "signal:SIGTERM"
    details = json.loads(row.algorithm_failure_details)
    assert details["emergency_step"] == step and details["reason"] == "SIGTERM"
    # the published pointer is the emergency step, and it verifies
    assert row.tensor_checkpoint_uri == f"{d}/{step}"
    tc = TensorCheckpointer(d)
    assert tc.latest_verified_step() == step
    tc.close()

    # the restart path resumes from the preemption point, not step 0
    resumed = run_workload(
        mnist_cfg(steps=10, checkpoint_every=50, checkpoint_dir=d),
        store=store, ctx=CTX, lifecycle=LifecycleContext(),
    )
    assert resumed["resumed_from"] == step and resumed["final_step"] == 10
    assert store.read_checkpoint(ALGORITHM, CTX.run_id).lifecycle_stage == (
        LifecycleStage.COMPLETED
    )


def test_emergency_save_skips_duplicate_of_committed_step(tmp_path):
    """A cancellation observed right after a boundary commit must not
    double-save the same step — the durable copy already exists."""
    store = seeded_store()
    lc = LifecycleContext()
    result = run_workload(
        # cancel while producing the batch for the LAST step: the boundary
        # commit for that step completes, then the loop drains
        mnist_cfg(steps=2, checkpoint_every=2, checkpoint_dir=str(tmp_path)),
        store=store, ctx=CTX, data=_cancelling_data(lc, 1), lifecycle=lc,
        telemetry=RecordingMetrics(),
    )
    assert result["preempted"] is True
    assert result["emergency_skipped"] is True and result["emergency_step"] == 2
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.PREEMPTED
    assert json.loads(row.algorithm_failure_details)["emergency_skipped"] is True


def test_bitflip_rollback_records_cause_everywhere(tmp_path, monkeypatch):
    """Silent corruption of the newest committed step: the next run rolls
    back exactly one step, quarantines the bad directory, and the cause
    lands in the summary, the metrics, and the ledger details."""
    d = str(tmp_path)
    store = seeded_store()
    monkeypatch.setenv("NEXUS_FAULT_MODE", "ckpt-bitflip")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "4")
    run_workload(
        mnist_cfg(steps=4, checkpoint_dir=d), store=store, ctx=CTX,
        lifecycle=LifecycleContext(),
    )
    monkeypatch.delenv("NEXUS_FAULT_MODE")
    monkeypatch.delenv("NEXUS_FAULT_STEP")
    # a restarted run arrives PREEMPTED (non-terminal), not COMPLETED — the
    # IsFinished guard would rightly drop writes from a finished run's ghost
    row = store.read_checkpoint(ALGORITHM, CTX.run_id).deep_copy()
    row.lifecycle_stage = LifecycleStage.PREEMPTED
    store.upsert_checkpoint(row)
    rec = RecordingMetrics()
    result = run_workload(
        mnist_cfg(steps=8, checkpoint_dir=d), store=store, ctx=CTX,
        lifecycle=LifecycleContext(), telemetry=rec,
    )
    assert result["resumed_from"] == 2  # rolled back exactly one step
    assert result["final_step"] == 8
    assert [e["cause"] for e in result["ckpt_rollbacks"]] == ["corrupt"]
    assert rec.tagged_counts[("train.ckpt_rollback", ("cause:corrupt",))] == 1
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.COMPLETED
    rollback = json.loads(row.algorithm_failure_details)["ckpt_rollback"]
    assert rollback[0]["step"] == 4 and rollback[0]["cause"] == "corrupt"
    assert any(n.startswith("4" + durability.QUARANTINE_SUFFIX) for n in os.listdir(d))
    # the rerun re-committed steps 4..8; the final pointer verifies
    assert row.tensor_checkpoint_uri == f"{d}/8"
    tc = TensorCheckpointer(d)
    assert tc.latest_verified_step() == 8
    tc.close()


def test_preemption_details_keep_rollback_evidence(tmp_path, monkeypatch):
    """preempted() rewrites the details column wholesale — a run that rolled
    back at restore time and is then preempted must keep BOTH stories: the
    emergency-save record AND the ckpt_rollback evidence RUNBOOK §11 tells
    operators to look for."""
    d = str(tmp_path)
    store = seeded_store()
    monkeypatch.setenv("NEXUS_FAULT_MODE", "ckpt-bitflip")
    monkeypatch.setenv("NEXUS_FAULT_STEP", "4")
    run_workload(
        mnist_cfg(steps=4, checkpoint_dir=d), store=store, ctx=CTX,
        lifecycle=LifecycleContext(),
    )
    monkeypatch.delenv("NEXUS_FAULT_MODE")
    monkeypatch.delenv("NEXUS_FAULT_STEP")
    row = store.read_checkpoint(ALGORITHM, CTX.run_id).deep_copy()
    row.lifecycle_stage = LifecycleStage.PREEMPTED
    store.upsert_checkpoint(row)
    lc = LifecycleContext()
    result = run_workload(
        mnist_cfg(steps=8, checkpoint_dir=d), store=store, ctx=CTX,
        data=_cancelling_data(lc, 3), lifecycle=lc, telemetry=RecordingMetrics(),
    )
    assert result["preempted"] is True and result["resumed_from"] == 2
    row = store.read_checkpoint(ALGORITHM, CTX.run_id)
    assert row.lifecycle_stage == LifecycleStage.PREEMPTED
    details = json.loads(row.algorithm_failure_details)
    assert details["emergency_step"] == result["final_step"]
    assert details["ckpt_rollback"][0]["step"] == 4
    assert details["ckpt_rollback"][0]["cause"] == "corrupt"


# -- subprocess drills: real crashes, real signals -----------------------------

# Shared phase-A entrypoint: the production run_workload path in a
# subprocess, because these drills kill the process (os._exit / SIGTERM).
_DRILL_SCRIPT = """
import sys
from tpu_nexus.parallel.smap import force_virtual_cpu_devices
force_virtual_cpu_devices(8)
from tpu_nexus.checkpoint.store import SqliteCheckpointStore
from tpu_nexus.models.registry import get_adapter
from tpu_nexus.parallel import MeshSpec
from tpu_nexus.parallel.distributed import ProcessContext
from tpu_nexus.workload.harness import WorkloadConfig, run_workload
from tpu_nexus.workload.health import HealthConfig

ledger, ckpt_dir, rid, algo, steps = sys.argv[1:6]
run_workload(
    WorkloadConfig(
        model=get_adapter("mnist"), mesh=MeshSpec(fsdp=-1), batch_size=8,
        seq_len=16, steps=int(steps), heartbeat_every=2, checkpoint_every=2,
        checkpoint_dir=ckpt_dir,
        health=HealthConfig(enabled=False),  # seed-program parity with mnist_cfg
    ),
    store=SqliteCheckpointStore(ledger),
    ctx=ProcessContext(run_id=rid, algorithm=algo, process_id=0,
                       num_processes=1, coordinator=None),
)
"""


def _run_drill(tmp_path, rid, steps, fault_mode, fault_step, timeout=240):
    env = dict(
        os.environ, NEXUS_FAULT_MODE=fault_mode, NEXUS_FAULT_STEP=str(fault_step)
    )
    return subprocess.run(
        [
            sys.executable, "-c", _DRILL_SCRIPT,
            str(tmp_path / "ledger.db"), str(tmp_path / "ckpt"), rid, ALGORITHM,
            str(steps),
        ],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


def _fresh_run(store, ckpt_dir, rid, steps=6):
    return run_workload(
        mnist_cfg(steps=steps, checkpoint_dir=str(ckpt_dir)),
        store=store,
        ctx=ProcessContext(run_id=rid, algorithm=ALGORITHM, process_id=0,
                           num_processes=1, coordinator=None),
        lifecycle=LifecycleContext(),
    )


def test_crash_mid_save_restart_resumes_bit_identical(tmp_path):
    """The flagship torn-save drill: die between the manifest temp write and
    the commit marker at the step-4 boundary, restart, and land a final loss
    bit-identical to a run that was never interrupted."""
    # uninterrupted baseline (same seeds, fresh directory)
    base_rid = str(uuid.uuid4())
    baseline = _fresh_run(seeded_store(rid=base_rid), tmp_path / "baseline-ckpt", base_rid)

    rid = str(uuid.uuid4())
    store = SqliteCheckpointStore(str(tmp_path / "ledger.db"))
    store.upsert_checkpoint(
        CheckpointedRequest(algorithm=ALGORITHM, id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
    )
    proc = _run_drill(tmp_path, rid, steps=6, fault_mode="ckpt-crash-mid-save", fault_step=4)
    assert proc.returncode == 1, (proc.returncode, proc.stderr[-2000:])

    ckpt_dir = tmp_path / "ckpt"
    row = store.read_checkpoint(ALGORITHM, rid)
    # the ledger never saw the torn step-4 URI: publish is behind the barrier
    assert row.tensor_checkpoint_uri == f"{ckpt_dir}/2"
    # the torn directory exists (payload written, marker absent)
    assert os.path.isdir(ckpt_dir / "4")
    with pytest.raises(CheckpointUncommitted):
        durability.verify_step(str(ckpt_dir / "4"), 4)

    # restart: resume from the last GOOD step, quarantine the torn one
    result = _fresh_run(store, ckpt_dir, rid)
    assert result["resumed_from"] == 2 and result["final_step"] == 6
    assert [e["cause"] for e in result["ckpt_rollbacks"]] == ["uncommitted"]
    assert result["loss"] == baseline["loss"], (result["loss"], baseline["loss"])
    assert os.path.isdir(str(ckpt_dir / "4") + durability.QUARANTINE_SUFFIX)
    row = store.read_checkpoint(ALGORITHM, rid)
    assert row.lifecycle_stage == LifecycleStage.COMPLETED
    assert row.tensor_checkpoint_uri == f"{ckpt_dir}/6"
    assert "ckpt_rollback" in row.algorithm_failure_details
    store.close()


def test_preempt_sigterm_during_save_window(tmp_path):
    """Graceful preemption landing INSIDE a save window: the handler catches
    the signal, the in-flight commit completes, the emergency path detects
    the already-durable same-step save and skips the duplicate, and the run
    exits PREEMPTED with the saved step in the ledger details."""
    rid = str(uuid.uuid4())
    store = SqliteCheckpointStore(str(tmp_path / "ledger.db"))
    store.upsert_checkpoint(
        CheckpointedRequest(algorithm=ALGORITHM, id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
    )
    proc = _run_drill(tmp_path, rid, steps=8, fault_mode="preempt-sigterm", fault_step=4)
    # the drain protocol catches the SIGTERM: clean exit, not a signal death
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-2000:])
    row = store.read_checkpoint(ALGORITHM, rid)
    assert row.lifecycle_stage == LifecycleStage.PREEMPTED
    assert row.algorithm_failure_cause == "signal:SIGTERM"
    details = json.loads(row.algorithm_failure_details)
    assert details["emergency_step"] == 4 and details["emergency_skipped"] is True
    assert row.tensor_checkpoint_uri == f"{tmp_path / 'ckpt'}/4"
    tc = TensorCheckpointer(str(tmp_path / "ckpt"))
    assert tc.latest_verified_step() == 4
    tc.close()
    store.close()


# -- slow tier: the full chaos matrix ------------------------------------------


@pytest.mark.slow
def test_crash_at_every_checkpoint_boundary(tmp_path):
    """The acceptance drill: ckpt-crash-mid-save at EVERY checkpoint
    boundary of a short run, then restart — always resumes from the last
    committed step, final loss bit-identical to the uninterrupted baseline,
    ledger URI always verifiable."""
    base_rid = str(uuid.uuid4())
    baseline = _fresh_run(seeded_store(rid=base_rid), tmp_path / "baseline-ckpt", base_rid)

    for boundary in (2, 4, 6):
        sub = tmp_path / f"boundary-{boundary}"
        sub.mkdir()
        rid = str(uuid.uuid4())
        store = SqliteCheckpointStore(str(sub / "ledger.db"))
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm=ALGORITHM, id=rid, lifecycle_stage=LifecycleStage.BUFFERED)
        )
        proc = _run_drill(sub, rid, steps=6, fault_mode="ckpt-crash-mid-save", fault_step=boundary)
        assert proc.returncode == 1, (boundary, proc.stderr[-2000:])
        row = store.read_checkpoint(ALGORITHM, rid)
        expected_resume = boundary - 2
        if expected_resume:
            assert row.tensor_checkpoint_uri == f"{sub / 'ckpt'}/{expected_resume}"
            assert durability.resolve_verified_uri(row.tensor_checkpoint_uri) == (
                row.tensor_checkpoint_uri
            )
        else:
            assert not row.tensor_checkpoint_uri  # died before the first commit
        result = _fresh_run(store, sub / "ckpt", rid)
        assert result["resumed_from"] == (expected_resume or None), boundary
        assert result["final_step"] == 6
        assert result["loss"] == baseline["loss"], boundary
        row = store.read_checkpoint(ALGORITHM, rid)
        assert row.lifecycle_stage == LifecycleStage.COMPLETED
        assert durability.resolve_verified_uri(row.tensor_checkpoint_uri) == (
            row.tensor_checkpoint_uri
        )
        store.close()


@pytest.mark.slow
def test_corruption_fuzz_seed_matrix(tmp_path):
    """≥100-seed fuzz over the verify/rollback machinery: random step
    series, random corruption of a random step, and the invariant that
    newest_verified_step always lands the newest step that still proves
    itself — never a corrupted one, never an older one than necessary."""
    import random

    ops = ("none", "bitflip", "remove-marker", "truncate", "delete-file", "delete-dir")
    for seed in range(100):
        rng = random.Random(seed)
        d = str(tmp_path / f"s{seed}")
        steps = sorted(rng.sample(range(1, 20), rng.randint(1, 3)))
        tc = TensorCheckpointer(d, max_to_keep=10)
        for s in steps:
            tc.save(s, tiny_state(s, scale=rng.random() + 0.5))
            tc.commit(s)
        tc.close()
        victim = rng.choice(steps)
        op = rng.choice(ops)
        step_dir = os.path.join(d, str(victim))
        if op == "bitflip":
            _flip_committed_leaf(step_dir)
        elif op == "remove-marker":
            os.remove(os.path.join(step_dir, durability.MANIFEST_NAME))
        elif op == "truncate":
            target = os.path.join(step_dir, durability.manifest_files(step_dir)[0])
            with open(target, "r+b") as fh:
                fh.truncate(max(os.path.getsize(target) - 1, 0))
        elif op == "delete-file":
            os.remove(os.path.join(step_dir, durability.manifest_files(step_dir)[-1]))
        elif op == "delete-dir":
            import shutil

            shutil.rmtree(step_dir)
        expected = [s for s in steps if op == "none" or s != victim]
        found, rollbacks = durability.newest_verified_step(d, quarantine=bool(seed % 2))
        assert found == (max(expected) if expected else None), (seed, op, victim, steps)
        if op in ("bitflip", "remove-marker", "truncate", "delete-file") and victim > (
            found or -1
        ):
            assert rollbacks and rollbacks[0]["step"] == victim, (seed, op)
        if found is not None:
            fresh = TensorCheckpointer(d, max_to_keep=10)
            restored = fresh.restore(tiny_state(0))
            assert int(restored["step"]) == found, (seed, op)
            fresh.close()
