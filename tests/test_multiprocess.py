"""True multi-process rehearsal: 2 jax.distributed CPU processes run one
sharded training run against a shared sqlite ledger (SURVEY §7.4 "testing
multi-host without TPUs"; BASELINE config #4 in miniature).

Validates for real (not simulated): coordinator bootstrap via the launcher
env contract, a process-spanning global mesh, cross-process collectives in
the train step, per-process data sharding, and concurrent per-host
heartbeats merging (not clobbering) in the ledger.
"""

import json
import os
import socket
import subprocess
import sys


from tpu_nexus.checkpoint.models import CheckpointedRequest, LifecycleStage
from tpu_nexus.checkpoint.store import SqliteCheckpointStore


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_rehearsal(tmp_path, tag, n_procs, devices_per_proc, extra_env):
    """Launch ``n_procs`` rehearsal workers against a fresh ledger; return
    (REHEARSAL_RESULT dicts, ledger db path, run_id, algorithm)."""
    db = str(tmp_path / f"ledger-{tag}.db")
    run_id, algorithm = f"rehearsal-{tag}", "llama-rehearsal"
    store = SqliteCheckpointStore(db)
    store.upsert_checkpoint(
        CheckpointedRequest(algorithm=algorithm, id=run_id, lifecycle_stage=LifecycleStage.BUFFERED)
    )
    store.close()
    port = free_port()
    env_base = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_proc}",
        "NEXUS_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "NEXUS_NUM_PROCESSES": str(n_procs),
        "NEXUS_RUN_ID": run_id,
        "NEXUS_ALGORITHM": algorithm,
        "NEXUS_REHEARSAL_DB": db,
        "NEXUS_BATCH": "4",
        # speed knob: shorter than the 256 default (and well inside tiny's
        # max_seq_len window) keeps the 2-process CPU run snappy
        "NEXUS_SEQ_LEN": "128",
        "NEXUS_STEPS": "6",
        "NEXUS_HEARTBEAT_EVERY": "2",
        **extra_env,
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tpu_nexus.workload.rehearsal"],
            env={**env_base, "NEXUS_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(n_procs)
    ]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} ({tag}) failed:\n{out[-3000:]}"
    results = []
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("REHEARSAL_RESULT ")][0]
        results.append(json.loads(line[len("REHEARSAL_RESULT "):]))
    return results, db, run_id, algorithm


def test_two_process_jax_distributed_run(tmp_path):
    results, db, run_id, algorithm = _run_rehearsal(
        tmp_path, "fsdp2x2", n_procs=2, devices_per_proc=2, extra_env={}
    )
    # SPMD: both processes computed the same global loss
    assert results[0]["final_step"] == results[1]["final_step"] == 6
    assert abs(results[0]["loss"] - results[1]["loss"]) < 1e-6

    store = SqliteCheckpointStore(db)
    cp = store.read_checkpoint(algorithm, run_id)
    assert cp.lifecycle_stage == LifecycleStage.COMPLETED
    # both hosts' heartbeats survived concurrent merging: each process has 2
    # virtual devices -> 4 distinct chip keys
    assert cp.per_chip_steps == {
        "host0/chip0": 6, "host0/chip1": 6, "host1/chip0": 6, "host1/chip1": 6,
    }, cp.per_chip_steps


def test_ring_attention_crosses_process_boundary(tmp_path):
    """sp=2 mesh spanning two jax.distributed processes (one device each):
    every ring step's ppermute crosses the process boundary — the topology
    the hand-written collective exists for (VERDICT r2 weak #6).  Loss must
    match a single-process run of the same model on the SAME global data
    (replicated-data mode uses the base seed in both topologies)."""
    ring, _, _, _ = _run_rehearsal(
        tmp_path, "ring-sp2", n_procs=2, devices_per_proc=1,
        extra_env={"NEXUS_MESH": "sp=2", "NEXUS_SEQ_LEN": "128"},
    )
    assert ring[0]["final_step"] == ring[1]["final_step"] == 6
    assert abs(ring[0]["loss"] - ring[1]["loss"]) < 1e-6  # SPMD agreement

    single, _, _, _ = _run_rehearsal(
        tmp_path, "single", n_procs=1, devices_per_proc=1,
        extra_env={"NEXUS_SEQ_LEN": "128"},
    )
    # ring-over-DCN vs plain single-device attention on identical data:
    # same training trajectory up to attention-impl numerics, which compound
    # over the 6 optimizer steps (single-step grad parity is asserted at
    # 2e-3 in test_parallel.py; observed trajectory delta here ~4e-4)
    assert abs(ring[0]["loss"] - single[0]["loss"]) < 2e-3, (ring[0], single[0])


def test_pipeline_handoff_crosses_process_boundary(tmp_path):
    """pp=2 mesh spanning two jax.distributed processes (one device each):
    every microbatch handoff — the CollectivePermute XLA derives from the
    pipeline's stage-axis roll — crosses the process boundary, the
    topology pipeline parallelism exists for (pp is the canonical
    over-DCN axis).  Loss must match a single-process run of the same
    model on the SAME global data."""
    pp, _, _, _ = _run_rehearsal(
        tmp_path, "pp2", n_procs=2, devices_per_proc=1,
        extra_env={"NEXUS_MESH": "pp=2,fsdp=1", "NEXUS_SEQ_LEN": "128"},
    )
    assert pp[0]["final_step"] == pp[1]["final_step"] == 6
    assert abs(pp[0]["loss"] - pp[1]["loss"]) < 1e-6  # SPMD agreement

    single, _, _, _ = _run_rehearsal(
        tmp_path, "pp-single", n_procs=1, devices_per_proc=1,
        extra_env={"NEXUS_SEQ_LEN": "128"},
    )
    # pipelined vs flat on identical data: same math (microbatch splits
    # only reorder f32 summation), so the trajectories agree tightly
    assert abs(pp[0]["loss"] - single[0]["loss"]) < 2e-3, (pp[0], single[0])
