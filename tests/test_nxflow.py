"""nxflow: the interprocedural engine (tools/nxlint/flow.py).

Covers the ISSUE 16 acceptance surface: the fails-closed failure modes
(unresolvable dispatch, star imports, graph-build crashes degrade loudly),
cycle termination, hash-keyed summary-cache invalidation, and — for every
rebuilt rule (NX007/NX008/NX010/NX014) — a BOTH-WAYS pair proving the
flow-backed pass flags a seeded violation the lexical pass provably
misses (and, where the flow pass is *more precise*, that it drops a
lexical false positive).  The repo-wide gate plus a wall-clock bound live
here too: interprocedural analysis only ships if the whole tree stays
clean AND fast.
"""

import ast
import os
import textwrap
import time

from tools.nxlint import Module, Project, lint_paths, lint_project
from tools.nxlint import flow as nxflow
from tools.nxlint.flow import FlowIntegrityRule, flow_for, summary_cache_stats
from tools.nxlint.rules_durability import (
    CheckpointPublishBarrierRule,
    ParamsSwapBarrierRule,
)
from tools.nxlint.rules_serving import DispatchLoopReadbackRule
from tools.nxlint.rules_tracing import HostSyncInJitRule

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_project(*files):
    modules = [
        Module("/virtual/" + rel, rel, textwrap.dedent(src)) for rel, src in files
    ]
    return Project("/virtual", modules)


def run_rule(rule_cls, project, flow_enabled=True):
    """Lint with a FRESH rule instance so toggling ``flow_enabled`` never
    leaks into the registry singletons other tests use."""
    rule = rule_cls()
    rule.flow_enabled = flow_enabled
    return lint_project(project, rules=[rule])


# -- failure modes (fails closed, degrades loudly) -----------------------------


def test_nx020_unbound_call_target_fails_closed():
    project = make_project(
        (
            "tpu_nexus/serving/helper.py",
            """
            def pump(batch):
                return mystery(batch)
            """,
        )
    )
    findings = lint_project(project, rules=[FlowIntegrityRule()])
    assert [f.rule_id for f in findings] == ["NX020"]
    assert "mystery" in findings[0].message
    assert "unresolvable dynamic dispatch" in findings[0].message


def test_nx020_star_import_fails_closed():
    project = make_project(
        (
            "tpu_nexus/workload/glue.py",
            """
            from os.path import *

            def f(p):
                return join(p, "x")
            """,
        )
    )
    findings = lint_project(project, rules=[FlowIntegrityRule()])
    # ONE finding, for the star import — the unbound-name check is skipped
    # (every star-provided name would be a false positive on top)
    assert [f.rule_id for f in findings] == ["NX020"]
    assert "star import" in findings[0].message


def test_nx020_out_of_scope_modules_are_exempt():
    project = make_project(
        (
            "pkg/helper.py",
            """
            from os.path import *

            def pump(batch):
                return mystery(batch)
            """,
        )
    )
    assert lint_project(project, rules=[FlowIntegrityRule()]) == []


def test_nx020_sanctioned_seam_suppressible_per_line():
    project = make_project(
        (
            "tpu_nexus/serving/helper.py",
            """
            def pump(batch):
                return mystery(batch)  # nxlint: disable=NX020 injected by the test harness
            """,
        )
    )
    assert lint_project(project, rules=[FlowIntegrityRule()]) == []


def test_graph_build_failure_reports_nx020_and_degrades_to_lexical(monkeypatch):
    """A crash in CallGraph construction must (a) surface as a named NX020
    finding and (b) leave the rebuilt rules running their lexical pass —
    never silently drop coverage."""

    def boom(project):
        raise RuntimeError("synthetic graph crash")

    monkeypatch.setattr(nxflow, "CallGraph", boom)
    project = make_project(
        (
            "tpu_nexus/workload/model.py",
            """
            import jax

            @jax.jit
            def step(x):
                return x.item()
            """,
        )
    )
    findings = lint_project(
        project, rules=[FlowIntegrityRule(), HostSyncInJitRule()]
    )
    by_rule = {f.rule_id for f in findings}
    assert by_rule == {"NX010", "NX020"}
    nx020 = next(f for f in findings if f.rule_id == "NX020")
    assert "call-graph construction failed" in nx020.message
    assert "RuntimeError" in nx020.message
    nx010 = next(f for f in findings if f.rule_id == "NX010")
    assert ".item()" in nx010.message  # the lexical fallback still caught it


def test_summarize_cycle_terminates_with_default():
    project = make_project(
        (
            "pkg/m.py",
            """
            def a(x):
                return b(x)

            def b(x):
                return a(x)
            """,
        )
    )
    graph = flow_for(project)
    info = graph.indexes["pkg/m.py"].functions["a"]

    def compute(fn, recurse):
        hit = False
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                for callee, _via in graph.resolve_call(node, fn.module):
                    hit = hit or bool(recurse(callee))
        return hit

    # a -> b -> a is cut by the cycle guard (default False), so the whole
    # summary is False and — crucially — the call returns at all
    assert graph.summarize(info, "test-cycle", compute, False) is False


def test_mutually_recursive_helpers_do_not_hang_the_barrier_rules():
    project = make_project(
        (
            "tpu_nexus/workload/pub.py",
            """
            def ping(ckpt):
                return pong(ckpt)

            def pong(ckpt):
                return ping(ckpt)

            def publish(reporter, ckpt, uri, step):
                ping(ckpt)
                reporter.tensor_checkpoint(uri, step)
            """,
        )
    )
    findings = run_rule(CheckpointPublishBarrierRule, project)
    # terminates, and the cyclic helpers summarize neutral: no barrier, so
    # the unbarriered publish is still flagged
    assert [f.rule_id for f in findings] == ["NX007"]


# -- hash-keyed summary cache ---------------------------------------------------

_ENGINE_SRC = """
from tpu_nexus.serving.pending import drain

class ServingEngine:
    def pump(self, pending):
        return drain(pending)
"""

_HELPER_OK = """
def drain(pending):
    return pending.value
"""

_HELPER_BAD = """
def drain(pending):
    return pending.value.item()
"""


def _nx014_over(helper_src):
    project = make_project(
        ("tpu_nexus/serving/engine.py", _ENGINE_SRC),
        ("tpu_nexus/serving/pending.py", helper_src),
    )
    return run_rule(DispatchLoopReadbackRule, project)


def test_summary_cache_hits_on_identical_sources_and_invalidates_on_edit():
    assert _nx014_over(_HELPER_OK) == []
    baseline = summary_cache_stats()["computes"]

    # identical project (fresh Modules, fresh CallGraph): the deep hash is
    # unchanged, so the summary comes straight from the cache
    assert _nx014_over(_HELPER_OK) == []
    assert summary_cache_stats()["computes"] == baseline

    # pure line motion (leading blank lines) — hashes exclude positions
    assert _nx014_over("\n\n" + _HELPER_OK) == []
    assert summary_cache_stats()["computes"] == baseline

    # a body edit changes the deep hash: recompute, and the verdict flips
    findings = _nx014_over(_HELPER_BAD)
    assert [f.rule_id for f in findings] == ["NX014"]
    assert "drain()" in findings[0].message
    assert summary_cache_stats()["computes"] > baseline


# -- both-ways: lexical pass misses, flow pass finds ----------------------------


def test_nx007_flow_catches_publish_through_wrapper_lexical_misses():
    """The sanctioned-seam refactor: the wrapper carries the per-line
    disable, so its own finding is suppressed — lexically the caller is
    invisible; through the graph the caller inherits the obligation."""
    project = make_project(
        (
            "tpu_nexus/workload/publish.py",
            """
            def publish_uri(reporter, uri, step):
                reporter.tensor_checkpoint(uri, step)  # nxlint: disable=NX007 sanctioned seam

            def after_save(ckpt, reporter, uri, step):
                ckpt.save(step)
                publish_uri(reporter, uri, step)
            """,
        )
    )
    assert run_rule(CheckpointPublishBarrierRule, project, flow_enabled=False) == []
    findings = run_rule(CheckpointPublishBarrierRule, project)
    assert [f.rule_id for f in findings] == ["NX007"]
    assert "publish_uri" in findings[0].message
    assert findings[0].line == 7  # the CALL site, not the wrapper


def test_nx007_flow_sees_barrier_inside_helper_lexical_false_positive():
    project = make_project(
        (
            "tpu_nexus/workload/publish.py",
            """
            def resolve(ckpt):
                return ckpt.latest_verified_step()

            def checked_publish(ckpt, reporter, uri):
                step = resolve(ckpt)
                reporter.tensor_checkpoint(uri, step)
            """,
        )
    )
    lexical = run_rule(CheckpointPublishBarrierRule, project, flow_enabled=False)
    assert [f.rule_id for f in lexical] == ["NX007"]  # blind to the helper
    assert run_rule(CheckpointPublishBarrierRule, project) == []


def test_nx008_flow_catches_bound_alias_swap_lexical_misses():
    project = make_project(
        (
            "tpu_nexus/serving/rollout.py",
            """
            def roll(engine, params):
                swap = engine.swap_params
                swap(params)
            """,
        )
    )
    assert run_rule(ParamsSwapBarrierRule, project, flow_enabled=False) == []
    findings = run_rule(ParamsSwapBarrierRule, project)
    assert [f.rule_id for f in findings] == ["NX008"]
    assert "bound alias of swap_params" in findings[0].message


def test_nx010_flow_follows_from_imported_helper_lexical_misses():
    project = make_project(
        (
            "tpu_nexus/workload/model.py",
            """
            import jax
            from tpu_nexus.workload.helpers import summarize

            @jax.jit
            def step(x):
                return summarize(x)
            """,
        ),
        (
            "tpu_nexus/workload/helpers.py",
            """
            def summarize(x):
                return x.item()
            """,
        ),
    )
    assert run_rule(HostSyncInJitRule, project, flow_enabled=False) == []
    findings = run_rule(HostSyncInJitRule, project)
    assert [f.rule_id for f in findings] == ["NX010"]
    assert findings[0].file == "tpu_nexus/workload/helpers.py"
    assert ".item()" in findings[0].message


def test_nx010_flow_follows_self_method_lexical_misses():
    project = make_project(
        (
            "tpu_nexus/workload/trainer.py",
            """
            import jax

            class Trainer:
                def build(self):
                    def step(x):
                        return self._tap(x)
                    return jax.jit(step)

                def _tap(self, x):
                    return float(x)
            """,
        )
    )
    assert run_rule(HostSyncInJitRule, project, flow_enabled=False) == []
    findings = run_rule(HostSyncInJitRule, project)
    assert [f.rule_id for f in findings] == ["NX010"]
    assert "float()" in findings[0].message


def test_nx014_flow_catches_readback_wrapped_in_sibling_module():
    findings_lexical_project = make_project(
        ("tpu_nexus/serving/engine.py", _ENGINE_SRC),
        ("tpu_nexus/serving/pending.py", _HELPER_BAD),
    )
    assert (
        run_rule(DispatchLoopReadbackRule, findings_lexical_project, flow_enabled=False)
        == []
    )
    findings = run_rule(DispatchLoopReadbackRule, findings_lexical_project)
    assert [f.rule_id for f in findings] == ["NX014"]
    assert "through the call graph" in findings[0].message
    assert findings[0].file == "tpu_nexus/serving/engine.py"


def test_nx014_flow_does_not_follow_executor_entry_points():
    """Method calls on non-engine objects are the blocking oracle path by
    contract — the graph must not drag them into dispatch-loop scope."""
    project = make_project(
        (
            "tpu_nexus/serving/engine.py",
            """
            from tpu_nexus.serving.executor import Executor

            class ServingEngine:
                def __init__(self):
                    self.executor = Executor()

                def pump(self, batch):
                    return self.executor.step(batch)
            """,
        ),
        (
            "tpu_nexus/serving/executor.py",
            """
            class Executor:
                def step(self, batch):
                    return batch.tokens.item()
            """,
        ),
    )
    assert run_rule(DispatchLoopReadbackRule, project) == []


# -- the repo-wide gate, timed --------------------------------------------------


def test_repo_wide_flow_lint_is_clean_and_under_60s():
    """The full interprocedural run over tpu_nexus/ AND tools/ must stay
    clean and complete well inside a minute — the pre-commit budget the
    --changed fast path assumes (the whole tree is always scanned; only
    reporting is filtered)."""
    start = time.monotonic()
    findings = lint_paths(
        [os.path.join(REPO_ROOT, "tpu_nexus"), os.path.join(REPO_ROOT, "tools")],
        root=REPO_ROOT,
    )
    elapsed = time.monotonic() - start
    rendered = "\n".join(f.render() for f in findings)
    assert findings == [], f"repo-wide nxlint regressed:\n{rendered}"
    assert elapsed < 60.0, f"repo-wide nxlint took {elapsed:.1f}s (budget: 60s)"
