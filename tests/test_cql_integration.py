"""Real-Scylla integration suite (VERDICT r1 missing #2).

The reference's whole test suite runs against a real Scylla at 127.0.0.1
(services/supervisor_test.go:36-39, docker-compose.yaml); this module is the
equivalent: it connects the hand-rolled CQL v4 wire client to a REAL
server's decoder — the loopback fake in test_cql.py can never prove the
encoder against a real implementation.

Skips cleanly when nothing listens on 127.0.0.1:9042 (developer laptops
without the compose stack).  CI sets ``NEXUS_REQUIRE_SCYLLA=1`` after
``docker compose up --wait`` succeeds, which turns an unreachable server
into a hard failure instead of a silent skip — the step gates something
real.
"""

import asyncio
import os
import socket
import threading
import uuid
from datetime import datetime, timedelta, timezone

import pytest

from tpu_nexus.checkpoint.cql import ScyllaCqlStore
from tpu_nexus.checkpoint.models import (
    JOB_LABEL_ALGORITHM_RUN,
    JOB_TEMPLATE_NAME_KEY,
    NEXUS_COMPONENT_LABEL,
    CheckpointedRequest,
    LifecycleStage,
)
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.k8s.fake import FakeKubeClient
from tpu_nexus.supervisor.service import ProcessingConfig, Supervisor
from tpu_nexus.supervisor.taxonomy import MSG_DEADLINE_EXCEEDED

HOST = os.environ.get("NEXUS_SCYLLA_HOST", "127.0.0.1")
PORT = int(os.environ.get("NEXUS_SCYLLA_PORT", "9042"))
REQUIRED = os.environ.get("NEXUS_REQUIRE_SCYLLA") == "1"


def _reachable() -> bool:
    try:
        with socket.create_connection((HOST, PORT), timeout=1.0):
            return True
    except OSError:
        return False


pytestmark = pytest.mark.skipif(
    not REQUIRED and not _reachable(),
    reason=f"no Scylla at {HOST}:{PORT} (start docker-compose, or set NEXUS_REQUIRE_SCYLLA=1 to fail hard)",
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def store():
    s = ScyllaCqlStore(hosts=[HOST], port=PORT, connect_timeout=5.0)
    s.apply_schema(
        "create keyspace if not exists nexus with replication = "
        "{'class': 'SimpleStrategy', 'replication_factor': 1}"
    )
    with open(os.path.join(_REPO, "tpu_nexus", "checkpoint", "schema.cql")) as fh:
        s.apply_schema(fh.read())
    with open(os.path.join(_REPO, "test-resources", "seed-checkpoints.cql")) as fh:
        s.apply_schema(fh.read())
    yield s
    s.close()


def _full_checkpoint(algorithm: str, rid: str) -> CheckpointedRequest:
    now = datetime(2026, 7, 30, 12, 0, 0, tzinfo=timezone.utc)
    return CheckpointedRequest(
        algorithm=algorithm,
        id=rid,
        lifecycle_stage=LifecycleStage.RUNNING,
        payload_uri="s3://payloads/run/input.json",
        result_uri="s3://results/run/output.json",
        algorithm_failure_cause="cause with 'quotes' and unicode ✓",
        algorithm_failure_details="trace line 1\nline 2; DROP TABLE x; --",
        received_by_host="receiver-0",
        received_at=now,
        sent_at=now + timedelta(seconds=3),
        applied_configuration='{"batch": 16}',
        configuration_overrides='{"lr": 0.0003}',
        content_hash="sha256:abcdef",
        last_modified=now + timedelta(seconds=5),
        tag="it-tag",
        api_version="v1",
        job_uid=str(uuid.uuid4()),
        parent="parent-run",
        payload_valid_for="24h",
        hlo_trace_ref="gs://traces/run/module_0001.hlo",
        per_chip_steps={"host0/chip0": 128, "host1/chip3": 127},
        tensor_checkpoint_uri="gs://ckpts/run/128",
        restart_count=2,
    )


class TestRoundTrip:
    def test_every_column_round_trips(self, store):
        """INSERT built by our encoder, decoded back by the real server —
        text (quotes/unicode/injection attempts), timestamps, map<text,
        bigint>, int."""
        rid = str(uuid.uuid4())
        cp = _full_checkpoint("it-roundtrip", rid)
        store.upsert_checkpoint(cp)
        got = store.read_checkpoint("it-roundtrip", rid)
        assert got is not None
        for field in (
            "algorithm", "id", "lifecycle_stage", "payload_uri", "result_uri",
            "algorithm_failure_cause", "algorithm_failure_details",
            "received_by_host", "applied_configuration", "configuration_overrides",
            "content_hash", "tag", "api_version", "job_uid", "parent",
            "payload_valid_for", "hlo_trace_ref", "tensor_checkpoint_uri",
            "restart_count", "per_chip_steps",
        ):
            assert getattr(got, field) == getattr(cp, field), field
        # timestamps: CQL stores millisecond precision
        for field in ("received_at", "sent_at", "last_modified"):
            want = getattr(cp, field)
            have = getattr(got, field)
            assert have is not None and abs((have - want).total_seconds()) < 0.001, field

    def test_missing_row_reads_none(self, store):
        assert store.read_checkpoint("it-roundtrip", str(uuid.uuid4())) is None

    def test_seeded_rows_visible(self, store):
        cp = store.read_checkpoint("it-algorithm", "00000000-0000-0000-0000-000000000008")
        assert cp is not None
        assert cp.lifecycle_stage == LifecycleStage.RUNNING
        assert cp.per_chip_steps == {"host0/chip0": 400, "host0/chip1": 400}
        assert cp.tensor_checkpoint_uri == "gs://ckpts/it/8/400"


class TestWrites:
    def test_update_fields_is_column_level(self, store):
        rid = str(uuid.uuid4())
        store.upsert_checkpoint(_full_checkpoint("it-update", rid))
        store.update_fields(
            "it-update",
            rid,
            {
                "lifecycle_stage": LifecycleStage.FAILED,
                "algorithm_failure_cause": "new cause",
                "last_modified": datetime.now(timezone.utc),
            },
        )
        got = store.read_checkpoint("it-update", rid)
        assert got.lifecycle_stage == LifecycleStage.FAILED
        assert got.algorithm_failure_cause == "new cause"
        # columns NOT named stay untouched — per_chip_steps especially
        assert got.per_chip_steps == {"host0/chip0": 128, "host1/chip3": 127}
        assert got.hlo_trace_ref == "gs://traces/run/module_0001.hlo"

    def test_compare_and_set_lwt_against_real_coordinator(self, store):
        """The LWT path (UPDATE … IF) against a real Paxos coordinator:
        applied on match, refused on mismatch, and two racing writers
        resolve to exactly one winner."""
        import threading

        rid = str(uuid.uuid4())
        store.upsert_checkpoint(_full_checkpoint("it-cas", rid))
        assert store.compare_and_set(
            "it-cas", rid,
            {"lifecycle_stage": LifecycleStage.RUNNING},
            {"lifecycle_stage": LifecycleStage.PREEMPTED, "restart_count": 1,
             "preempted_generation": "gen-1"},
        )
        got = store.read_checkpoint("it-cas", rid)
        assert got.lifecycle_stage == LifecycleStage.PREEMPTED
        assert got.restart_count == 1 and got.preempted_generation == "gen-1"
        # stale expectation refused by the coordinator
        assert not store.compare_and_set(
            "it-cas", rid,
            {"lifecycle_stage": LifecycleStage.RUNNING},
            {"lifecycle_stage": LifecycleStage.FAILED},
        )
        # two racing increments from the same observed count: one winner
        results = []
        barrier = threading.Barrier(2)

        def racer():
            barrier.wait()
            results.append(
                store.compare_and_set(
                    "it-cas", rid,
                    {"restart_count": 1},
                    {"restart_count": 2},
                )
            )

        threads = [threading.Thread(target=racer) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == [False, True]
        assert store.read_checkpoint("it-cas", rid).restart_count == 2

    def test_update_fields_rejects_unknown_column(self, store):
        with pytest.raises(Exception):
            store.update_fields("it-update", str(uuid.uuid4()), {"evil; DROP": "x"})

    def test_merge_chip_steps_from_two_threads(self, store):
        """The map-append path under real concurrency: two hosts report
        disjoint chips in parallel; no write clobbers the other's cells."""
        rid = str(uuid.uuid4())
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm="it-merge", id=rid, lifecycle_stage=LifecycleStage.RUNNING)
        )
        # one store per thread: CqlConnection serializes on a lock, separate
        # connections make the writes truly concurrent on the server
        def work(host_idx: int):
            s = ScyllaCqlStore(hosts=[HOST], port=PORT)
            try:
                for step in range(1, 21):
                    s.merge_chip_steps(
                        "it-merge", rid, {f"host{host_idx}/chip{c}": step for c in range(4)}
                    )
            finally:
                s.close()

        threads = [threading.Thread(target=work, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got = store.read_checkpoint("it-merge", rid)
        want = {f"host{h}/chip{c}": 20 for h in range(2) for c in range(4)}
        assert got.per_chip_steps == want

    def test_secondary_indexes(self, store):
        rid = str(uuid.uuid4())
        cp = _full_checkpoint("it-index", rid)
        cp.tag = f"tag-{rid[:8]}"
        cp.received_by_host = f"host-{rid[:8]}"
        store.upsert_checkpoint(cp)
        assert [c.id for c in store.query_by_tag(cp.tag)] == [rid]
        assert [c.id for c in store.query_by_host(cp.received_by_host)] == [rid]
        assert rid in [c.id for c in store.query_by_stage(LifecycleStage.RUNNING)]


class TestSupervisorOnScylla:
    async def test_e2e_deadline_exceeded(self, store):
        """One full supervision scenario with the ledger on real Scylla —
        the reference's own test topology (fake k8s + real CQL),
        services/supervisor_test.go:36-44."""
        algorithm = "it-supervise"
        rid = str(uuid.uuid4())
        store.upsert_checkpoint(
            CheckpointedRequest(algorithm=algorithm, id=rid, lifecycle_stage=LifecycleStage.RUNNING)
        )
        labels = {
            NEXUS_COMPONENT_LABEL: JOB_LABEL_ALGORITHM_RUN,
            JOB_TEMPLATE_NAME_KEY: algorithm,
        }
        client = FakeKubeClient(
            {
                "Job": [
                    {
                        "kind": "Job",
                        "metadata": {
                            "name": rid, "namespace": "nexus",
                            "uid": str(uuid.uuid4()), "labels": labels,
                        },
                        "status": {},
                    }
                ],
                "Event": [
                    {
                        "kind": "Event",
                        "metadata": {"name": f"evt-{rid[:8]}", "namespace": "nexus"},
                        "reason": "DeadlineExceeded",
                        "message": "Job was active longer than specified deadline",
                        "type": "Warning",
                        "involvedObject": {"kind": "Job", "name": rid, "namespace": "nexus"},
                    }
                ],
            }
        )
        supervisor = Supervisor(client, store, "nexus", resync_period=timedelta(0))
        supervisor.init(
            ProcessingConfig(
                failure_rate_base_delay=timedelta(milliseconds=5),
                failure_rate_max_delay=timedelta(milliseconds=50),
                rate_limit_elements_per_second=0,
                workers=2,
            )
        )
        ctx = LifecycleContext()
        task = asyncio.create_task(supervisor.start(ctx))
        await asyncio.sleep(0.05)
        assert await supervisor.idle(timeout=15)
        ctx.cancel()
        await task
        cp = store.read_checkpoint(algorithm, rid)
        assert cp.lifecycle_stage == LifecycleStage.DEADLINE_EXCEEDED
        assert cp.algorithm_failure_cause == MSG_DEADLINE_EXCEEDED
        assert client.deleted("Job") == [rid]

    async def test_two_replicas_race_real_coordinator(self, store):
        """VERDICT r4 Missing #3, real-engine leg: two supervisors with
        SEPARATE wire clients drive one duplicated event storm against the
        real coordinator's LWT arbitration — every run lands terminal
        exactly once and the loser replicas' refusals are visible as
        ledger_cas_conflicts (when the interleaving produced any; the
        arbitration guarantee, not the conflict count, is the invariant)."""
        algorithm = "it-replica-race"
        runs = [str(uuid.uuid4()) for _ in range(6)]
        labels = {
            NEXUS_COMPONENT_LABEL: JOB_LABEL_ALGORITHM_RUN,
            JOB_TEMPLATE_NAME_KEY: algorithm,
        }
        objects = {"Job": [], "Event": []}
        for rid in runs:
            store.upsert_checkpoint(
                CheckpointedRequest(
                    algorithm=algorithm, id=rid, lifecycle_stage=LifecycleStage.RUNNING
                )
            )
            objects["Job"].append(
                {
                    "kind": "Job",
                    "metadata": {
                        "name": rid, "namespace": "nexus",
                        "uid": str(uuid.uuid4()), "labels": labels,
                    },
                    "status": {},
                }
            )
        client = FakeKubeClient(objects)

        replicas, ctxs, tasks, stores = [], [], [], []
        for _ in range(2):
            s = ScyllaCqlStore(hosts=[HOST], port=PORT, connect_timeout=5.0)
            stores.append(s)
            sup = Supervisor(client, s, "nexus", resync_period=timedelta(0))
            sup.init(
                ProcessingConfig(
                    failure_rate_base_delay=timedelta(milliseconds=5),
                    failure_rate_max_delay=timedelta(milliseconds=50),
                    rate_limit_elements_per_second=0,
                    workers=2,
                    failure_lane_workers=4,
                )
            )
            ctx = LifecycleContext()
            replicas.append(sup)
            ctxs.append(ctx)
            tasks.append(asyncio.create_task(sup.start(ctx)))
        await asyncio.sleep(0.05)

        for host in range(4):  # 4 host-duplicates per run, both replicas
            for rid in runs:
                client.inject(
                    "ADDED", "Event",
                    {
                        "kind": "Event",
                        "metadata": {
                            "name": f"evt-{rid[:8]}-{host}", "namespace": "nexus",
                        },
                        "reason": "DeadlineExceeded",
                        "message": f"host-{host}: deadline",
                        "type": "Warning",
                        "involvedObject": {"kind": "Job", "name": rid, "namespace": "nexus"},
                    },
                )
        for sup in replicas:
            assert await sup.idle(timeout=30)
        for ctx in ctxs:
            ctx.cancel()
        for task in tasks:
            await task
        for s in stores:
            s.close()

        for rid in runs:
            cp = store.read_checkpoint(algorithm, rid)
            assert cp.lifecycle_stage == LifecycleStage.DEADLINE_EXCEEDED, rid
            # the partial order + LWT made every duplicate a no-op: the
            # terminal details were written exactly once (the winning CAS
            # carries the cause; a double-apply would also have doubled
            # restart bookkeeping on preempt scenarios — asserted in the
            # fake-arbiter storm which can script the interleaving)
            assert cp.algorithm_failure_cause == MSG_DEADLINE_EXCEEDED
            assert 1 <= client.deleted("Job").count(rid) <= 2
