"""Tests for the rate-limited actor (nexus-core DefaultPipelineStageActor
parity, SURVEY §2.3): multi-worker draining, exponential failure backoff
re-delivery, token-bucket rate limiting, next-stage chaining."""

import asyncio
import time
from datetime import timedelta

from tpu_nexus.core.pipeline import PipelineStageActor, TokenBucket
from tpu_nexus.core.signals import LifecycleContext
from tpu_nexus.core.telemetry import RecordingMetrics


async def run_actor_until_idle(actor, ctx, timeout=10.0):
    task = asyncio.create_task(actor.start(ctx))
    await actor.wait_started()
    assert await actor.idle(timeout=timeout)
    ctx.cancel()
    await task


async def test_processes_all_elements_and_chains_next_stage():
    seen = []
    sink = PipelineStageActor(
        "sink", process_fn=lambda x: seen.append(x), rate_per_second=0, workers=1
    )
    doubler = PipelineStageActor(
        "double", process_fn=lambda x: x * 2, rate_per_second=0, workers=4, next_stage=sink
    )
    ctx = LifecycleContext()
    for i in range(20):
        doubler.receive(i)  # pre-start buffering must work (informers race startup)
    t1 = asyncio.create_task(doubler.start(ctx))
    t2 = asyncio.create_task(sink.start(ctx))
    await doubler.wait_started()
    assert await doubler.idle()
    assert await sink.idle()
    ctx.cancel()
    await asyncio.gather(t1, t2)
    assert sorted(seen) == [i * 2 for i in range(20)]
    assert doubler.processed == 20


async def test_failure_redelivery_with_backoff():
    attempts = {}
    metrics = RecordingMetrics()

    def flaky(x):
        attempts[x] = attempts.get(x, 0) + 1
        if attempts[x] < 3:
            raise RuntimeError("transient")
        return x

    actor = PipelineStageActor(
        "flaky",
        process_fn=flaky,
        rate_per_second=0,
        workers=2,
        failure_base_delay=timedelta(milliseconds=5),
        failure_max_delay=timedelta(milliseconds=20),
        metrics=metrics,
    )
    ctx = LifecycleContext()
    actor.receive("a")
    actor.receive("b")
    await run_actor_until_idle(actor, ctx)
    assert attempts == {"a": 3, "b": 3}
    assert actor.failed == 4  # two failures per element
    assert actor.processed == 2
    assert metrics.counters["flaky.processed"] == 2
    assert metrics.counters["flaky.failures"] == 4


async def test_token_bucket_throttles():
    bucket = TokenBucket(rate=100.0, burst=1)
    t0 = time.monotonic()
    for _ in range(6):
        await bucket.acquire()
    elapsed = time.monotonic() - t0
    # 1 burst token + 5 refills at 100/s => >= ~50ms
    assert elapsed >= 0.04


async def test_token_bucket_burst_admits_concurrently():
    """Waiters sleep OUTSIDE the bucket lock: concurrent acquirers on a
    drained bucket share the refill stream instead of serializing behind a
    single lock-holding sleeper, and burst capacity is spendable at once."""
    import asyncio

    bucket = TokenBucket(rate=100.0, burst=8)
    t0 = time.monotonic()
    await asyncio.gather(*(bucket.acquire() for _ in range(8)))
    assert time.monotonic() - t0 < 0.05  # all 8 burst tokens spent at once
    # drained: 4 concurrent waiters need 4 refills at 100/s ~= 40ms total,
    # which also proves no waiter sat behind another's full sleep chain
    t0 = time.monotonic()
    await asyncio.gather(*(bucket.acquire() for _ in range(4)))
    elapsed = time.monotonic() - t0
    assert 0.02 <= elapsed < 0.5


async def test_token_bucket_refunds_cancelled_waiters():
    """A cancelled waiter must hand its admission slot back: a burst of
    cancellations (task teardown) must not throttle later acquires for work
    that never ran (ADVICE r2)."""
    import asyncio

    bucket = TokenBucket(rate=10.0, burst=1)
    await bucket.acquire()  # spend the burst token; bucket now drained
    # 20 waiters would reserve slots 2s into the future...
    waiters = [asyncio.create_task(bucket.acquire()) for _ in range(20)]
    await asyncio.sleep(0.01)
    for w in waiters:
        w.cancel()
    await asyncio.gather(*waiters, return_exceptions=True)
    # ...but every cancelled slot was refunded, so a fresh acquire waits at
    # most ~1 refill (100ms), not the 2s the abandoned slots reserved
    t0 = time.monotonic()
    await bucket.acquire()
    assert time.monotonic() - t0 < 0.5


async def test_rate_limited_actor_respects_rate():
    done = []
    actor = PipelineStageActor(
        "limited", process_fn=lambda x: done.append(x), rate_per_second=50, burst=1, workers=4
    )
    ctx = LifecycleContext()
    for i in range(10):
        actor.receive(i)
    t0 = time.monotonic()
    await run_actor_until_idle(actor, ctx)
    # 9 post-burst elements at 50/s => at least ~180ms
    assert time.monotonic() - t0 >= 0.15
    assert len(done) == 10


async def test_async_process_fn():
    out = []

    async def work(x):
        await asyncio.sleep(0.001)
        out.append(x)
        return x

    actor = PipelineStageActor("async", process_fn=work, rate_per_second=0, workers=3)
    ctx = LifecycleContext()
    for i in range(9):
        actor.receive(i)
    await run_actor_until_idle(actor, ctx)
    assert sorted(out) == list(range(9))


async def test_post_start_runs_once_workers_up():
    ran = asyncio.Event()
    actor = PipelineStageActor("ps", process_fn=lambda x: x, rate_per_second=0, workers=1)
    ctx = LifecycleContext()

    async def post_start():
        ran.set()

    task = asyncio.create_task(actor.start(ctx, post_start))
    await asyncio.wait_for(ran.wait(), timeout=2)
    ctx.cancel()
    await task
