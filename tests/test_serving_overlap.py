"""Overlapped dispatch + in-jit multi-step decode (ISSUE 12).

Layers, cheapest first:

* DispatchPipeline ledger units (serving/overlap.py);
* engine behavior against the deterministic FakeExecutor: one-step-late
  materialization, slot refill, deferred drain ("no request may lose its
  final in-flight token"), mode validation;
* a seeded fuzz: random traffic × random cancels × {overlap} ×
  {decode_steps}, asserting after EVERY step that slot AND pipeline
  accounting are consistent, and at the end that every request is
  terminal and every non-cancelled output equals the synchronous oracle
  run of the same schedule;
* the token-identity gate: real-model greedy outputs of the new engine
  modes pinned token-identical to one-shot ``generate`` across
  {contiguous, paged} × {bf16, int8-KV} × {xla, pallas-interpret}
  (pallas rows in f32 — the PR 6 near-tie precedent: the reordering is
  layout noise, not a semantics difference), plus in-device stop-token
  detection against the sync oracle.
"""

import dataclasses
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_nexus.models import LlamaConfig
from tpu_nexus.models.generate import generate
from tpu_nexus.models.llama import llama_init
from tpu_nexus.serving import (
    DispatchPipeline,
    ModelExecutor,
    PagedModelExecutor,
    PendingStep,
    PipelineError,
    RequestState,
    ServingEngine,
)

from tests.test_serving_engine import FakeExecutor


def make_engine(num_slots=2, max_len=64, decode_steps=1, overlap=True, stop_token=-1):
    fake = FakeExecutor(
        num_slots, max_len, decode_steps=decode_steps, stop_token=stop_token
    )
    return ServingEngine(fake, overlap=overlap)


def drive(eng, max_steps=2000):
    while eng.has_work:
        assert eng.steps < max_steps, "engine did not drain"
        eng.step()
        eng.slots.verify_consistent()
        eng._pipeline.verify_consistent()


def expected_tokens(prompt, n):
    first = (int(prompt[-1]) + 1) % 1000
    return [first + i for i in range(n)]


# -- DispatchPipeline ledger units ---------------------------------------------


class TestDispatchPipeline:
    def _pending(self, slots, assumed):
        return PendingStep(
            thunk=lambda: None,
            snapshot={s: object() for s in slots},
            order=list(slots),
            cursor_base=np.zeros(4, np.int64),
            assumed=np.asarray(assumed),
        )

    def test_push_credits_inflight_and_clears_overrides(self):
        pipe = DispatchPipeline(4)
        pipe.note_override(1)
        p = self._pending([0, 1], [2, 3, 0, 0])
        pipe.push(p)
        assert pipe.overridden == set()
        assert list(pipe.inflight) == [2, 3, 0, 0]
        assert pipe.deferred_slots == 2
        pipe.credit(p, 0)
        pipe.credit(p, 1)
        assert pipe.deferred_slots == 0
        pipe.verify_consistent()

    def test_note_retired_zeroes_and_overrides(self):
        pipe = DispatchPipeline(4)
        pipe.push(self._pending([2], [0, 0, 5, 0]))
        pipe.note_retired(2)
        assert pipe.inflight[2] == 0
        assert 2 in pipe.overridden
        assert pipe.override_mask().tolist() == [False, False, True, False]

    def test_pop_empty_raises(self):
        with pytest.raises(PipelineError, match="no pending"):
            DispatchPipeline(2).pop()

    def test_verify_catches_stray_inflight(self):
        pipe = DispatchPipeline(2)
        pipe.inflight[1] = 3  # budget with no covering dispatch
        with pytest.raises(PipelineError, match="no pending dispatch"):
            pipe.verify_consistent()

    def test_verify_catches_depth_runaway(self):
        pipe = DispatchPipeline(2)
        for _ in range(3):
            pipe.push(self._pending([], [0, 0]))
        with pytest.raises(PipelineError, match="depth"):
            pipe.verify_consistent()

    def test_clear_resets_everything(self):
        pipe = DispatchPipeline(2)
        pipe.push(self._pending([0], [4, 0]))
        pipe.note_override(1)
        pipe.clear()
        assert pipe.depth == 0 and pipe.deferred_slots == 0
        assert pipe.overridden == set()


# -- engine behavior against the fake ------------------------------------------


class TestOverlappedEngine:
    def test_finishes_with_identical_tokens(self):
        eng = make_engine()
        req = eng.submit(np.array([7]), 5)
        drive(eng)
        assert req.state == RequestState.FINISHED
        assert req.output_tokens == expected_tokens([7], 5)

    def test_materialization_is_one_step_late(self):
        eng = make_engine()
        req = eng.submit(np.array([7]), 4)
        eng.step()  # admit + first token + dispatch #1 (nothing materialized)
        assert len(req.output_tokens) == 1
        assert eng._pipeline.depth == 1 and eng._pipeline.deferred_slots == 1
        eng.step()  # dispatch #2, materialize #1
        assert len(req.output_tokens) == 2
        assert eng.metrics.deferred_slots == 1

    def test_sync_mode_never_uses_the_pipeline(self):
        eng = make_engine(overlap=False, decode_steps=1)
        eng.submit(np.array([7]), 5)
        drive(eng)
        assert eng.executor.scan_calls == 0
        assert eng._pipeline.depth == 0 and eng._pipeline.deferred_slots == 0

    def test_multistep_amortizes_dispatches(self):
        eng = make_engine(decode_steps=4, overlap=False)
        req = eng.submit(np.array([7]), 9)  # 1 prefill token + 8 scanned
        drive(eng)
        assert req.output_tokens == expected_tokens([7], 9)
        assert eng.executor.scan_calls == 2  # ceil(8 / 4), not 8

    def test_deferred_drain_keeps_the_final_in_flight_token(self):
        """The drain/SIGTERM acceptance: a request whose FINAL token is
        riding an unmaterialized dispatch must finish, not evict, even at
        zero grace — the fence materializes before any drain decision."""
        eng = make_engine()
        req = eng.submit(np.array([7]), 3)
        eng.step()  # token 1 (prefill) + dispatch carrying token 2
        eng.step()  # dispatch token 3, materialize token 2
        assert len(req.output_tokens) == 2
        assert eng._pipeline.deferred_slots == 1  # the FINAL token in flight
        summary = eng.drain(grace_s=0.0)
        assert req.state == RequestState.FINISHED
        assert req.output_tokens == expected_tokens([7], 3)
        assert summary["drain_evicted"] == 0
        assert eng._pipeline.depth == 0

    def test_cancel_between_dispatch_and_materialize_skips_the_lane(self):
        eng = make_engine()
        a = eng.submit(np.array([7]), 8)
        b = eng.submit(np.array([17]), 8)
        eng.step()
        eng.step()
        frozen = len(a.output_tokens)
        eng.cancel(a.request_id)
        eng.step()  # cancel sweep retires a; pending lane for a is skipped
        assert a.state == RequestState.CANCELLED
        assert len(a.output_tokens) == frozen  # nothing emitted post-cancel
        drive(eng)
        assert b.state == RequestState.FINISHED
        assert b.output_tokens == expected_tokens([17], 8)

    def test_slot_refill_overrides_the_device_carry(self):
        """A freed slot's next tenant must decode from ITS OWN first token,
        not the previous tenant's stale device carry."""
        eng = make_engine(num_slots=1)
        a = eng.submit(np.array([7]), 3)
        b = eng.submit(np.array([307]), 3)
        drive(eng)
        assert a.output_tokens == expected_tokens([7], 3)
        assert b.output_tokens == expected_tokens([307], 3)

    def test_stop_token_freezes_and_finishes(self):
        prompt = np.array([7])
        stop = expected_tokens(prompt, 9)[3]
        eng = make_engine(decode_steps=3, stop_token=stop)
        req = eng.submit(prompt, 9)
        drive(eng)
        assert req.state == RequestState.FINISHED
        assert req.output_tokens == expected_tokens(prompt, 4)  # stop emitted

    def test_stop_token_on_first_token_finishes_at_admission(self):
        prompt = np.array([7])
        stop = (int(prompt[-1]) + 1) % 1000
        eng = make_engine(stop_token=stop)
        req = eng.submit(prompt, 9)
        drive(eng)
        assert req.state == RequestState.FINISHED
        assert req.output_tokens == [stop]

    def test_spec_and_overlap_mutually_exclusive(self):
        fake = FakeExecutor(2, 64)
        with pytest.raises(ValueError, match="mutually exclusive"):
            ServingEngine(fake, spec_k=2, drafter=object(), overlap=True)

    def test_overlap_requires_step_scan(self):
        class Bare:
            num_slots, max_len = 2, 64

        with pytest.raises(ValueError, match="step_scan"):
            ServingEngine(Bare(), overlap=True)

    def test_quiesce_and_swap_fence_the_pipeline(self):
        eng = make_engine()
        req = eng.submit(np.array([7]), 6)
        eng.step()
        eng.step()
        assert eng._pipeline.depth == 1
        eng.quiesce(grace_s=1000.0, max_steps=100)
        assert eng._pipeline.depth == 0
        assert req.state == RequestState.FINISHED
        assert req.output_tokens == expected_tokens([7], 6)
        eng.swap_params = eng.swap_params  # the engine-level seam
        eng.resume_admission()


# -- fuzz: overlap/multi-step vs the synchronous oracle -------------------------


def _run_schedule(overlap, decode_steps, seed):
    rng = random.Random(seed)
    n_requests = rng.randint(3, 10)
    specs = [
        (rng.randint(1, 900), rng.randint(1, 12)) for _ in range(n_requests)
    ]
    cancel_at = {
        i: rng.randint(1, 6) for i in range(n_requests) if rng.random() < 0.25
    }
    eng = make_engine(
        num_slots=rng.choice([1, 2, 3]), decode_steps=decode_steps,
        overlap=overlap,
    )
    reqs = []
    step = 0
    submitted = 0
    while submitted < n_requests or eng.has_work:
        while submitted < n_requests and rng.random() < 0.7:
            tok, gen = specs[submitted]
            reqs.append(eng.submit(np.array([tok]), gen))
            submitted += 1
        for i, r in enumerate(reqs):
            if cancel_at.get(i) == step:
                eng.cancel(r.request_id)
        eng.step()
        eng.slots.verify_consistent()
        eng._pipeline.verify_consistent()
        step += 1
        assert step < 1000, "fuzz engine did not drain"
    return specs, reqs


@pytest.mark.parametrize("decode_steps", [1, 3])
def test_overlap_fuzz_matches_oracle(decode_steps):
    """Random traffic + random cancels: every request terminal, pipeline
    drained, and non-cancelled outputs EXACTLY the deterministic fake's
    sequence — one-step-late materialization loses and invents nothing."""
    for seed in range(12):
        specs, reqs = _run_schedule(True, decode_steps, seed)
        for (tok, gen), req in zip(specs, reqs):
            assert req.is_terminal()
            full = expected_tokens([tok], gen)
            if req.state == RequestState.FINISHED:
                assert req.output_tokens == full, (seed, req.request_id)
            else:  # cancelled mid-flight: a clean prefix, never garbage
                assert req.state == RequestState.CANCELLED
                assert req.output_tokens == full[: len(req.output_tokens)]


# -- token-identity gate: real model, all layouts/dtypes/kernels ---------------


def _interpret_works() -> bool:
    from tpu_nexus.ops.decode_attention import decode_attention

    try:
        q = jnp.ones((1, 1, 2, 8), jnp.float32)
        kv = jnp.ones((1, 16, 2, 8), jnp.float32)
        decode_attention(q, kv, kv, jnp.asarray(4, jnp.int32), interpret=True)
        return True
    except Exception:  # noqa: BLE001 - any interpreter failure means "skip env"
        return False


_CAN_INTERPRET = _interpret_works()

CFG = LlamaConfig.tiny()
PARAMS = llama_init(jax.random.PRNGKey(0), CFG)
# pallas rows run f32 — the PR 6 precedent: the kernel's online-softmax
# split order is layout noise (~1e-7 in f32) that in bf16 can flip a
# near-tied argmax; the OVERLAP/MULTI-STEP semantics under test are
# dtype-independent.
CFG_F32 = dataclasses.replace(CFG, dtype=jnp.float32)


def _cfg_for(kernel: str) -> LlamaConfig:
    return CFG if kernel == "xla" else CFG_F32


def _kernels():
    yield "xla"
    if _CAN_INTERPRET:
        yield "pallas"


@pytest.mark.parametrize("kv_quant", ["", "int8"])
@pytest.mark.parametrize("kernel", list(_kernels()))
@pytest.mark.parametrize("paged", [False, True])
def test_overlap_multistep_matches_generate(paged, kernel, kv_quant):
    """The ISSUE 12 token-identity gate: the fully-composed new mode
    (overlap + decode_steps=3) over {contiguous, paged} × {bf16, int8-KV}
    × {xla, pallas-interpret}, with num_slots < requests so slot reuse
    and mid-flight admission ride the deferred pipeline too."""
    S, T, N = 8, 5, 4
    rng = np.random.default_rng(11)
    lens = [5, 8, 3, 7]
    prompts = [
        rng.integers(1, CFG.vocab_size, size=n).astype(np.int32) for n in lens
    ]
    cfg = _cfg_for(kernel)
    kwargs = dict(
        num_slots=2, max_len=S + T, kv_quant=kv_quant,
        decode_kernel=kernel, decode_steps=3,
    )
    if paged:
        executor = PagedModelExecutor(PARAMS, cfg, page_size=4, **kwargs)
    else:
        executor = ModelExecutor(PARAMS, cfg, **kwargs)
    eng = ServingEngine(executor, overlap=True)
    reqs = [eng.submit(p, T) for p in prompts]
    eng.run_until_drained(max_steps=2000)
    eng._pipeline.verify_consistent()
    if paged:
        eng.paged.verify_consistent()
    for i, req in enumerate(reqs):
        solo = np.asarray(
            generate(
                PARAMS, jnp.asarray(prompts[i][None]), cfg,
                max_new_tokens=T, max_len=S + T,
                kv_quant=kv_quant, decode_kernel=kernel,
            )
        )[0]
        np.testing.assert_array_equal(
            np.asarray(req.output_tokens), solo,
            err_msg=f"request {i} (paged={paged} kernel={kernel} kv={kv_quant})",
        )


@pytest.mark.parametrize("overlap,decode_steps", [(True, 1), (False, 4), (True, 4)])
def test_engine_modes_match_sync_oracle(overlap, decode_steps):
    """Each new mode against the UNCHANGED synchronous k=1 engine on the
    same request set (bf16/XLA): the oracle path is byte-identical to the
    pre-ISSUE-12 engine, so agreement here pins the whole family."""
    S, T, N = 8, 6, 5
    rng = np.random.default_rng(3)
    prompts = rng.integers(1, CFG.vocab_size, size=(N, S)).astype(np.int32)

    def run(ov, k):
        ex = ModelExecutor(PARAMS, CFG, num_slots=2, max_len=S + T, decode_steps=k)
        eng = ServingEngine(ex, overlap=ov)
        reqs = [eng.submit(prompts[i], T) for i in range(N)]
        eng.run_until_drained(max_steps=2000)
        return [r.output_tokens for r in reqs]

    assert run(overlap, decode_steps) == run(False, 1)


def test_stop_token_real_model_matches_truncated_oracle():
    """In-device stop detection: outputs are the sync no-stop oracle's
    stream truncated at (and including) the first stop token."""
    S, T = 8, 6
    rng = np.random.default_rng(5)
    prompts = rng.integers(1, CFG.vocab_size, size=(2, S)).astype(np.int32)
    ref = np.asarray(
        generate(PARAMS, jnp.asarray(prompts), CFG, max_new_tokens=T, max_len=S + T)
    )
    stop = int(ref[0][2])  # a token that really occurs mid-stream
    ex = ModelExecutor(
        PARAMS, CFG, num_slots=2, max_len=S + T, decode_steps=3, stop_token=stop
    )
    eng = ServingEngine(ex, overlap=True)
    reqs = [eng.submit(prompts[i], T) for i in range(2)]
    eng.run_until_drained(max_steps=2000)
    for i, req in enumerate(reqs):
        full = list(ref[i])
        expect = full[: full.index(stop) + 1] if stop in full else full
        assert req.output_tokens == expect, i
        assert req.state == RequestState.FINISHED
