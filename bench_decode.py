"""Serving-path decode benchmark with an explicit HBM roofline.

Decode is memory-bound: every step re-reads the weights and the KV cache.
VERDICT r4 asked for the floor to be PRICED, not invoked — so every row
this script prints carries:

  * ``ms_step``   — decode-only ms/token, measured by the long-minus-short
                    subtraction (whole-``generate`` calls at 288 vs 32 new
                    tokens; identical prompt and max_len, so prefill +
                    dispatch overheads cancel);
  * ``floor_ms``  — (weight bytes + KV-cache bytes touched per step) / HBM
                    bandwidth.  Weight bytes = every param leaf the step
                    reads (the tied embedding IS the head matmul operand;
                    the token-embedding *gather* of B rows is negligible
                    and not counted separately).  KV bytes = the full
                    [L, B, max_len, Hkv, D] K+V buffers — the masked
                    attention einsum is static over max_len, so the whole
                    buffer crosses HBM each step (+ scale planes when the
                    cache is int8);
  * ``x_floor``   — ms_step / floor_ms, the honest "how done is this" number.

Variants: bf16 | int8 weights | int4 weights | int8 KV cache | int8
weights + int8 KV (``NEXUS_DECODE_VARIANTS`` to restrict,
comma-separated).

Weight-quantized variants (``int8w``/``int4w``) are additionally measured
per WEIGHT-matmul implementation: the fused dequant-inside-matmul pallas
kernel (``ops/quant_matmul.py``) AND the XLA gather/astype fallback, so
the kernel's win is read off the same table as the decode-attention
kernel's (``wq_kernel`` field; ``NEXUS_DECODE_WQ_KERNELS`` to restrict).
Off TPU the "pallas" rows run the kernel in interpret mode — a
correctness floor, not a speed number (PERF.md prices the TPU roofline).

Every variant is measured per decode-attention implementation — the fused
split-KV pallas kernel (``ops/decode_attention.py``) AND the masked-einsum
XLA fallback — so each row's ``x_floor`` carries a ``kernel`` field and
the kernel's win is read off the same table (``NEXUS_DECODE_KERNELS`` to
restrict, comma-separated; defaults to ``pallas,xla`` on TPU, ``xla``
elsewhere).

One JSON line per (shape, variant, kernel) to stdout; v5e HBM defaults to
819 GB/s (``NEXUS_BENCH_HBM_GBPS`` to override).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

_HBM_GBPS = (
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v5p", 2765.0),
    ("v6", 1640.0),
    ("v4", 1228.0),
)


def _chip_hbm_gbps(device) -> float:
    env = os.environ.get("NEXUS_BENCH_HBM_GBPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    for sub, bw in _HBM_GBPS:
        if sub in kind:
            return bw
    return 0.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tpu_nexus.models import LlamaConfig
    from tpu_nexus.models.generate import generate
    from tpu_nexus.models.llama import llama_init
    from tpu_nexus.models.quant import quantize_params, quantized_bytes

    on_tpu = jax.default_backend() in ("tpu", "axon")
    model = os.environ.get("NEXUS_DECODE_MODEL", "nexus_1b")
    if model == "nexus_moe":
        import dataclasses

        from tpu_nexus.models import MoeConfig

        base = MoeConfig.nexus_moe() if on_tpu else MoeConfig.tiny()
        # decode normalizes to dropless scatter dispatch (generate._decode_cfg)
        cfg = dataclasses.replace(base, max_seq_len=max(base.max_seq_len, 32768))
    else:
        cfg = LlamaConfig.nexus_1b_long() if on_tpu else LlamaConfig.tiny()
    # (batch, prompt_len, max_len): the r4 serving table shapes plus the
    # long-context rows the KV-carry fix was measured on
    if on_tpu:
        shapes = [
            (64, 128, 416),
            (8, 2048, 2048 + 288),
            (1, 8192, 8192 + 288),
        ]
    else:
        shapes = [(2, 16, 16 + 40)]
    env_shapes = os.environ.get("NEXUS_DECODE_SHAPES")
    if env_shapes:
        shapes = [tuple(int(x) for x in s.split("x")) for s in env_shapes.split(",")]

    known_variants = ("bf16", "int8w", "int4w", "int8kv", "int8w+int8kv")
    variants = list(known_variants)
    env_variants = os.environ.get("NEXUS_DECODE_VARIANTS")
    if env_variants:
        variants = env_variants.split(",")
        bad = [v for v in variants if v not in known_variants]
        if bad:
            raise SystemExit(
                f"unknown NEXUS_DECODE_VARIANTS {bad}; use {', '.join(known_variants)}"
            )

    # the per-row decode_kernel argument labels the "kernel" field; the
    # NEXUS_DECODE_KERNEL escape hatch only replaces the "auto" default
    # (cached_attention precedence), so rows cannot be silently re-routed
    # — surface a notice anyway so an operator watching stderr isn't
    # surprised that their env var doesn't apply here
    if os.environ.get("NEXUS_DECODE_KERNEL"):
        print("bench_decode: NEXUS_DECODE_KERNEL ignored (rows pin the kernel per row)",
              file=sys.stderr)
    kernels = ("pallas", "xla") if on_tpu else ("xla",)
    env_kernels = os.environ.get("NEXUS_DECODE_KERNELS")
    if env_kernels:
        kernels = tuple(env_kernels.split(","))
        bad = [kn for kn in kernels if kn not in ("auto", "pallas", "xla")]
        if bad:
            raise SystemExit(
                f"unknown NEXUS_DECODE_KERNELS {bad}; use auto, pallas, xla"
            )

    long_n, short_n = (288, 32) if on_tpu else (40, 8)
    if os.environ.get("NEXUS_DECODE_WINDOW"):
        long_n, short_n = (int(x) for x in os.environ["NEXUS_DECODE_WINDOW"].split(","))
    bw = _chip_hbm_gbps(jax.devices()[0]) * 1e9

    if model == "nexus_moe":
        from tpu_nexus.models.moe import moe_init as _init
    else:
        _init = llama_init
    params = _init(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)
    qparams4 = quantize_params(params, mode="int4")
    w_bytes_full = quantized_bytes(params)
    w_bytes_int8 = quantized_bytes(qparams)
    w_bytes_int4 = quantized_bytes(qparams4)

    # weight-matmul implementations for the quantized-weight variants:
    # "pallas" pins the fused dequant kernel (interpret mode off TPU),
    # "xla" pins the gather/astype fallback — the kernel-on/off pair the
    # ISSUE 17 BENCH artifact reads its win from
    wq_kernels = ("pallas", "xla")
    env_wq = os.environ.get("NEXUS_DECODE_WQ_KERNELS")
    if env_wq:
        wq_kernels = tuple(env_wq.split(","))
        bad = [kn for kn in wq_kernels if kn not in ("auto", "pallas", "xla")]
        if bad:
            raise SystemExit(
                f"unknown NEXUS_DECODE_WQ_KERNELS {bad}; use auto, pallas, xla"
            )

    l, hkv, d = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim

    def kv_bytes(batch: int, max_len: int, quant: bool) -> int:
        per_elem = 1 if quant else jnp.dtype(cfg.dtype).itemsize
        values = 2 * l * batch * max_len * hkv * d * per_elem  # K + V
        scales = 2 * l * batch * max_len * hkv * 4 if quant else 0
        return values + scales

    for batch, prompt_len, max_len in shapes:
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
        )
        for variant in variants:
          for kernel in kernels:
           for wq_kernel in (wq_kernels if "int8w" in variant or "int4w" in variant else ("",)):
            if "int4w" in variant:
                p = qparams4
            elif "int8w" in variant:
                p = qparams
            else:
                p = params
            kv_quant = "int8" if "int8kv" in variant else ""

            def run(n_tokens, p=p, kv_quant=kv_quant, kernel=kernel,
                    wq_kernel=wq_kernel):
                # weight_einsum reads NEXUS_QUANT_KERNEL at TRACE time, so
                # pinning it around the jit call routes this row's weight
                # matmuls; restored after tracing so rows stay independent
                prev = os.environ.get("NEXUS_QUANT_KERNEL")
                if wq_kernel:
                    os.environ["NEXUS_QUANT_KERNEL"] = wq_kernel
                try:
                    fn = jax.jit(
                        functools.partial(
                            generate, cfg=cfg, max_new_tokens=n_tokens,
                            max_len=max_len, kv_quant=kv_quant,
                            decode_kernel=kernel,
                        ),
                        static_argnames=(),
                    )
                    out = fn(p, prompt)
                finally:
                    if wq_kernel:
                        if prev is None:
                            os.environ.pop("NEXUS_QUANT_KERNEL", None)
                        else:
                            os.environ["NEXUS_QUANT_KERNEL"] = prev
                # warmup must ALSO sync via a device->host pull: plain
                # block_until_ready under-syncs on remote-relay backends
                # (bench.py), leaking warmup execution into the timed window
                int(out[0, -1])
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    out = fn(p, prompt)
                    int(out[0, -1])
                    best = min(best, time.perf_counter() - t0)
                return best

            t_long, t_short = run(long_n), run(short_n)
            ms_step = (t_long - t_short) * 1000.0 / (long_n - short_n)
            # time-to-first-token estimate: the short call minus its decode
            # share — includes prefill, sampling setup, and dispatch
            ttft_ms = max(t_short * 1000.0 - short_n * ms_step, 0.0)
            if "int4w" in variant:
                w_bytes = w_bytes_int4
            elif "int8w" in variant:
                w_bytes = w_bytes_int8
            else:
                w_bytes = w_bytes_full
            total_bytes = w_bytes + kv_bytes(batch, max_len, bool(kv_quant))
            floor_ms = total_bytes / bw * 1000.0 if bw else 0.0
            print(json.dumps({
                "metric": "decode_ms_per_step",
                "model": model,
                "batch": batch, "prompt": prompt_len, "max_len": max_len,
                "variant": variant,
                "kernel": kernel,
                "wq_kernel": wq_kernel,
                "ms_step": round(ms_step, 3),
                "floor_ms": round(floor_ms, 3),
                "x_floor": round(ms_step / floor_ms, 2) if floor_ms else 0.0,
                "tok_s": round(batch * 1000.0 / ms_step, 1) if ms_step > 0 else 0.0,
                "ttft_ms_est": round(ttft_ms, 1),
                "weight_gb": round(w_bytes / 1e9, 3),
                "kv_gb": round(kv_bytes(batch, max_len, bool(kv_quant)) / 1e9, 3),
            }), flush=True)


if __name__ == "__main__":
    main()
