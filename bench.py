"""Headline benchmark: supervised JAX training throughput, tokens/sec/chip.

Runs the full workload harness path (sharded train step, flash-attention
kernel, remat, heartbeats into an in-memory ledger) on the real device(s) and
prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": F, ...}

``vs_baseline``: the reference (SneaksAndData/nexus-supervisor) publishes no
performance numbers (BASELINE.md — its `published` map is empty), so there is
no reference number to ratio against; by convention we report the ratio vs
the recorded target in BASELINE.json `published` when present, else 1.0.

``mfu``: model FLOPs utilization — the standard 6N-parameter + causal
attention FLOP model (forward + 2x backward; remat recompute deliberately
EXCLUDED, per the usual MFU convention) divided by the chip's peak bf16
FLOP/s.  Peak is looked up from the device kind and can be overridden with
``NEXUS_BENCH_PEAK_TFLOPS``.

Model: ``LlamaConfig.nexus_1b`` — ~1B params, head_dim 128 (pallas flash
kernel on the hot path), bf16 params+optimizer, sized for one v5e chip.

Tuning knobs (all env, all optional — defaults are the tuned configuration):
  NEXUS_BENCH_MODEL     nexus_1b (default) | nexus_moe (MoeConfig.nexus_moe:
                        8 experts, top-2, dropless grouped-matmul dispatch;
                        MFU counts ACTIVE params per the MoE convention)
  NEXUS_BENCH_BATCH     per-chip batch size (default 16; moe default 64)
  NEXUS_BENCH_CAPACITY  MoE capacity factor override (default from config)
  NEXUS_BENCH_DISPATCH  MoE dispatch override: scatter | sort | gmm
  NEXUS_BENCH_SEQ       sequence length (default 2048)
  NEXUS_BENCH_STEPS     timed steps (default 10)
  NEXUS_BENCH_REMAT     remat policy: dots | attn_out | qkv | nothing
  NEXUS_BENCH_UNROLL    layer-scan unroll factor (default from config)
  NEXUS_BENCH_OPTIMIZER adamw (default) | adamw-bf16 (bf16 moments, frees
                        ~3.8 GB for remat/unroll headroom) | adafactor
  NEXUS_BENCH_PROFILE   directory: capture a jax.profiler trace of the timed
                        window into it (artifact for perf archaeology)
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

# The FLOP model + peak table live in tpu_nexus.workload.goodput (ISSUE
# 15 made them a library concern — the training harness computes live MFU
# from the same estimator this bench reports, so the two can never use
# different conventions).  Re-exported here for the historical import path.
from tpu_nexus.workload.goodput import chip_peak_flops, model_flops_per_token  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tpu_nexus.models import LlamaConfig
    from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
    from tpu_nexus.workload.data import synthetic_tokens
    from tpu_nexus.workload.train import TrainConfig, init_train_state, make_train_step

    n_chips = jax.device_count()
    on_tpu = jax.default_backend() in ("tpu", "axon")
    model = os.environ.get("NEXUS_BENCH_MODEL", "nexus_1b")
    if on_tpu:
        if model == "nexus_moe":
            from tpu_nexus.models import MoeConfig

            cfg = MoeConfig.nexus_moe()
            per_chip_batch, seq, steps, warmup = 64, 2048, 10, 2
        else:
            cfg = LlamaConfig.nexus_1b()
            per_chip_batch, seq, steps, warmup = 16, 2048, 10, 2
    else:  # CPU smoke: keep it honest but small
        if model == "nexus_moe":
            from tpu_nexus.models import MoeConfig

            cfg = MoeConfig.tiny()
        else:
            cfg = LlamaConfig.tiny()
        per_chip_batch, seq, steps, warmup = 1, 128, 10, 2
    per_chip_batch = int(os.environ.get("NEXUS_BENCH_BATCH", per_chip_batch))
    seq = int(os.environ.get("NEXUS_BENCH_SEQ", seq))
    steps = int(os.environ.get("NEXUS_BENCH_STEPS", steps))
    if getattr(cfg, "max_seq_len", 0) and seq > cfg.max_seq_len:
        # the bench is a tuning harness: widen the context-window guard
        # explicitly instead of failing it (production workloads pick a
        # preset whose max_seq_len covers their sequence, e.g. nexus_1b_long)
        cfg = dataclasses.replace(cfg, max_seq_len=seq)
    if os.environ.get("NEXUS_BENCH_REMAT"):
        cfg = dataclasses.replace(cfg, remat_policy=os.environ["NEXUS_BENCH_REMAT"])
    if os.environ.get("NEXUS_BENCH_UNROLL"):
        cfg = dataclasses.replace(cfg, scan_unroll=int(os.environ["NEXUS_BENCH_UNROLL"]))
    if os.environ.get("NEXUS_BENCH_CAPACITY") and getattr(cfg, "n_experts", 0):
        cfg = dataclasses.replace(cfg, capacity_factor=float(os.environ["NEXUS_BENCH_CAPACITY"]))
    if os.environ.get("NEXUS_BENCH_DISPATCH") and getattr(cfg, "n_experts", 0):
        cfg = dataclasses.replace(cfg, dispatch=os.environ["NEXUS_BENCH_DISPATCH"])
    # per-chip batch is fixed and the batch shards over dp*fsdp = all chips,
    # so the global batch divides the mesh at any chip count
    batch = per_chip_batch * n_chips

    tcfg = TrainConfig(
        warmup_steps=10,
        total_steps=1000,
        ce_chunk=int(os.environ.get("NEXUS_BENCH_CE_CHUNK", "256")),
        optimizer=os.environ.get("NEXUS_BENCH_OPTIMIZER", "adamw"),
    )
    mesh = build_mesh(MeshSpec(fsdp=-1))
    rules = LOGICAL_RULES_FSDP_TP
    state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, rules)
    step_fn = make_train_step(cfg, tcfg, mesh, rules)
    data = synthetic_tokens(batch, seq, cfg.vocab_size, seed=0)

    profile_dir = os.environ.get("NEXUS_BENCH_PROFILE")

    # sync via float() (device->host transfer): steps chain through the
    # donated state, so pulling the final loss waits for the whole window.
    # (block_until_ready alone does not synchronize through remote-relay
    # backends — measured 150x-too-fast numbers with it.)
    with mesh:
        for _ in range(warmup):
            state, metrics = step_fn(state, jnp.asarray(next(data)))
        float(metrics["loss"])
        if profile_dir:
            jax.profiler.start_trace(profile_dir)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, jnp.asarray(next(data)))
        float(metrics["loss"])
        elapsed = time.perf_counter() - t0
        if profile_dir:
            jax.profiler.stop_trace()

    tokens_per_sec = batch * seq * steps / elapsed
    per_chip = tokens_per_sec / n_chips

    peak = chip_peak_flops(jax.devices()[0])
    mfu = per_chip * model_flops_per_token(cfg, seq) / peak if peak else 0.0

    baseline = 0.0
    try:
        with open(os.path.join(os.path.dirname(__file__) or ".", "BASELINE.json")) as fh:
            published = json.load(fh).get("published") or {}
        baseline = float(published.get("tokens_per_sec_per_chip", 0.0))
    except (OSError, ValueError):
        pass
    vs_baseline = per_chip / baseline if baseline else 1.0

    record = {
        "metric": "supervised_jax_tokens_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "mfu": round(mfu, 4),
        "model": model,
        "batch_per_chip": per_chip_batch,
        "seq": seq,
        "remat_policy": cfg.remat_policy,
        "chips": n_chips,
    }
    if getattr(cfg, "n_experts", 0):
        record["dispatch"] = cfg.dispatch
        if cfg.dispatch == "gmm":
            record["dropless"] = True  # gmm ignores capacity_factor
        else:
            record["capacity_factor"] = cfg.capacity_factor
    print(json.dumps(record))


if __name__ == "__main__":
    main()
