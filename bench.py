"""Headline benchmark: supervised JAX training throughput, tokens/sec/chip.

Runs the full workload harness path (sharded train step, flash-attention
kernel, remat, heartbeats into an in-memory ledger) on the real device(s) and
prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline``: the reference (SneaksAndData/nexus-supervisor) publishes no
performance numbers (BASELINE.md — its `published` map is empty), so there is
no reference number to ratio against; by convention we report the ratio vs
the recorded target in BASELINE.json `published` when present, else 1.0.

Model: ``LlamaConfig.nexus_1b`` — ~1B params, head_dim 128 (pallas flash
kernel on the hot path), bf16 params+optimizer, sized for one v5e chip.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tpu_nexus.models import LlamaConfig
    from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
    from tpu_nexus.workload.data import synthetic_tokens
    from tpu_nexus.workload.train import TrainConfig, init_train_state, make_train_step

    n_chips = jax.device_count()
    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu:
        cfg = LlamaConfig.nexus_1b()
        batch, seq, steps, warmup = 16 * n_chips, 2048, 10, 2
    else:  # CPU smoke: keep it honest but small
        cfg = LlamaConfig.tiny()
        batch, seq, steps, warmup = 1 * n_chips, 128, 10, 2
    # per-chip batch is fixed and the batch shards over dp*fsdp = all chips,
    # so the global batch divides the mesh at any chip count

    tcfg = TrainConfig(warmup_steps=10, total_steps=1000)
    mesh = build_mesh(MeshSpec(fsdp=-1))
    rules = LOGICAL_RULES_FSDP_TP
    state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, rules)
    step_fn = make_train_step(cfg, tcfg, mesh, rules)
    data = synthetic_tokens(batch, seq, cfg.vocab_size, seed=0)

    # sync via float() (device->host transfer): steps chain through the
    # donated state, so pulling the final loss waits for the whole window.
    # (block_until_ready alone does not synchronize through remote-relay
    # backends — measured 150x-too-fast numbers with it.)
    with mesh:
        for _ in range(warmup):
            state, metrics = step_fn(state, jnp.asarray(next(data)))
        float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, jnp.asarray(next(data)))
        float(metrics["loss"])
        elapsed = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / elapsed
    per_chip = tokens_per_sec / n_chips

    baseline = 0.0
    try:
        with open(os.path.join(os.path.dirname(__file__) or ".", "BASELINE.json")) as fh:
            published = json.load(fh).get("published") or {}
        baseline = float(published.get("tokens_per_sec_per_chip", 0.0))
    except (OSError, ValueError):
        pass
    vs_baseline = per_chip / baseline if baseline else 1.0

    print(
        json.dumps(
            {
                "metric": "supervised_jax_tokens_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
