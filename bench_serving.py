"""Serving bench: continuous batching vs the lockstep round loop.

The point of ``tpu_nexus/serving`` in one number: under MIXED generation
lengths, the lockstep loop (``run_serving``-style rounds — every request
in a round waits for the round's longest generation) burns decode steps
on finished rows, while the engine retires and refills slots every
iteration.  Both schedulers process the SAME request set at the SAME slot
count on the SAME jitted model functions; the JSON artifact records both
completed-tokens/s numbers plus the engine's TTFT/TPOT p50/p99 under
Poisson arrivals.

Usage: ``python bench_serving.py`` — prints one JSON line and writes the
artifact itself (``NEXUS_SERVING_OUT``, default BENCH_SERVING_r06.json;
do NOT shell-redirect stdout onto the same file).  Pure CPU, tiny config,
fixed seeds, finishes in seconds (CI hygiene like bench_latency.py).
Knobs: ``NEXUS_SERVING_REQUESTS`` / ``NEXUS_SERVING_SLOTS`` /
``NEXUS_SERVING_ARRIVAL_RPS``.

``--spec-k`` (ISSUE 11) benches SPECULATIVE decoding: the same engine,
same jitted model, on a repetitive-suffix workload (prompts ending in a
repeated motif, long generations — the traffic n-gram drafting exists
for), spec-off vs spec-on with the ngram drafter.  The artifact records
completed-tokens/s both ways plus the HONEST acceptance rate (padding
guesses count as proposed; emission-capped tokens do not count as
accepted).  Note the economics: on this CPU bench a W-token verify costs
nearly W times a decode step (compute-bound), so the win shown here is
the floor — on TPU, decode is HBM-bandwidth-bound on weight/cache
streaming and a verify step costs barely more than a decode step, so the
same acceptance rate buys ~(1 + accepted/step) instead.  Artifact:
``NEXUS_SERVING_SPEC_OUT``, default BENCH_SERVING_SPEC_r08.json.  Knobs:
``NEXUS_SPEC_BENCH_K`` / ``NEXUS_SPEC_BENCH_GEN`` /
``NEXUS_SPEC_BENCH_REQUESTS``.

``--overlap`` / ``--decode-steps`` (ISSUE 12) benches the HOST TAX: the
same mixed-length request set through the synchronous k=1 engine, the
overlapped-dispatch engine (decode step N+1 dispatched while N's tokens
are in flight, deferred readback), and overlapped + in-jit multi-step
decode (``lax.scan`` of k steps per dispatch) — greedy outputs asserted
token-identical across all three modes, so the ratio is pure dispatch
hiding.  Artifact: ``NEXUS_SERVING_ASYNC_OUT``, default
BENCH_SERVING_ASYNC_r09.json.  Knob: ``NEXUS_OVERLAP_BENCH_STEPS``.

``--mesh tp=N`` (ISSUE 13) benches TENSOR-PARALLEL sharded serving: the
same offline request set through the single-chip engine and the sharded
executors (serving/sharded.py) on an N-way virtual CPU mesh, contiguous
AND paged, with a cross-mode token-identity assert.  Honest framing: a
virtual CPU "mesh" timeshares the same host cores, so the ratio measures
the GSPMD partition/dispatch OVERHEAD of sharding, never a TP speedup —
the artifact's value is the parity row + the dispatch counts (the r9
precedent: at tiny scale this bench prices host work, and the sharded
engine must pay the same dispatch count as the single-chip engine).  The
bench model runs f32: TP psum reordering resolves exact bf16 argmax ties
differently (docs/SERVING.md "Sharded serving").  Artifact:
``NEXUS_SERVING_TP_OUT``, default BENCH_SERVING_TP_r10.json.

``--fleet`` (ISSUE 19) benches the FLEET ROUTER: the same skewed Poisson
arrival schedule (rate doubled over the middle third) through a
capacity-skewed fleet — one replica at a quarter of the slots with a
bounded queue — under blind round-robin vs pressure routing (load-ranked
candidates + shed-and-retry + prefix affinity).  The headline is
goodput-at-SLO (tokens from requests meeting the TTFT/TPOT targets per
wall second); outputs are asserted token-identical across policies, and
a shared-prefix fan-out section shows affinity co-locating the fan-out
(fleet prefix hits = fanout - 1) where rotation re-prefills the shared
prompt per replica.  Artifact: ``NEXUS_FLEET_OUT``, default
BENCH_FLEET_r14.json.  Knobs: ``NEXUS_FLEET_REPLICAS`` /
``NEXUS_FLEET_WEAK_SLOTS`` / ``NEXUS_FLEET_REQUESTS`` /
``NEXUS_FLEET_TTFT_SLO_S`` / ``NEXUS_FLEET_TPOT_SLO_S``.

``--disagg`` (ISSUE 20) benches DISAGGREGATED prefill/decode serving:
the same mixed long-prefill/short-decode Poisson schedule through the
same two-replica hardware budget — two FUSED paged replicas vs one
PREFILL + one DECODE replica with the sealed KV-block handoff between
them (serving/handoff.py).  The headline is TTFT p99: on the fused side
every admission waits for ticks that interleave long prefills with the
whole decode batch, while the prefill replica's tenancy is TRANSIENT
(slot + blocks released the moment the payload is extracted), so
admissions never queue behind decode work.  Arrivals are scheduled in
TICK-space with the middle fifth compressed into one burst, so the
contended regime is machine-speed independent; the burst peak overflows
the decode pool by a few requests on purpose — the recorded
degrade-to-fused path is priced into the disaggregated percentiles, not
hidden.  Outputs are asserted token-identical across modes —
disaggregation moves WHERE the KV lives, never WHAT gets decoded — and
the artifact records the handoff/fallback accounting (every request
either completes the handoff or is RECORDED degrading).  Artifact:
``NEXUS_DISAGG_OUT``, default BENCH_DISAGG_r15.json.  Knobs:
``NEXUS_DISAGG_BENCH_REQUESTS`` / ``NEXUS_DISAGG_BENCH_ARRIVAL_PER_TICK``
/ ``NEXUS_DISAGG_BENCH_SLOTS``.

``--shared-prefix`` (ISSUE 6) instead benches the PAGED engine on the
millions-of-users workload: one long system prompt, high fan-out, short
unique tails.  Both engines get the SAME KV HBM budget (``slots ×
max_len`` cache rows); the slot-granular engine spends it on
``NUM_SLOTS`` whole rows while the paged engine spends it on
``page_size``-token blocks — shared prompt blocks are prefilled ONCE and
referenced by every request, so the same bytes host several times more
concurrent requests.  Artifact: ``NEXUS_SERVING_PREFIX_OUT``, default
BENCH_SERVING_PREFIX_r07.json.  Knobs: ``NEXUS_PREFIX_FANOUT`` /
``NEXUS_PREFIX_SHARED_LEN`` / ``NEXUS_PREFIX_PAGE``.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_nexus.models import LlamaConfig
from tpu_nexus.models.generate import generate
from tpu_nexus.models.llama import llama_init
from tpu_nexus.serving import (
    ModelExecutor,
    PagedModelExecutor,
    RequestState,
    ServingEngine,
    ServingMetrics,
)

SEED = 0
N_REQUESTS = int(os.environ.get("NEXUS_SERVING_REQUESTS", "48"))
NUM_SLOTS = int(os.environ.get("NEXUS_SERVING_SLOTS", "8"))
#: default arrival rate sits UNDER the CPU engine's measured capacity
#: (~30 req/s at this config) so the TTFT/TPOT percentiles reflect
#: scheduling latency, not unbounded queue buildup from overload
ARRIVAL_RPS = float(os.environ.get("NEXUS_SERVING_ARRIVAL_RPS", "24"))
PROMPT_RANGE = (4, 16)
#: mixed-length traffic: the variance is what lockstep rounds pay for —
#: nearly every lockstep round contains one 64-token generation and runs
#: its short requests' slots idle to the end of it
GEN_CHOICES = (2, 8, 64)
MAX_LEN = PROMPT_RANGE[1] + max(GEN_CHOICES)


def bench_model() -> LlamaConfig:
    """Small enough to finish in seconds on CPU, big enough (~6 ms/decode
    step at batch 8) that a decode step costs real compute relative to the
    engine's per-iteration host work — at `LlamaConfig.tiny` scale the
    bench would measure Python dispatch, not scheduling."""
    return LlamaConfig(
        vocab_size=512, hidden=256, n_layers=4, n_heads=8, n_kv_heads=4,
        head_dim=32, intermediate=512, max_seq_len=2 * MAX_LEN, remat=False,
    )


def make_requests(rng, n=None):
    reqs = []
    for _ in range(N_REQUESTS if n is None else n):
        plen = int(rng.integers(PROMPT_RANGE[0], PROMPT_RANGE[1] + 1))
        reqs.append(
            {
                "prompt": rng.integers(1, 256, size=plen).astype(np.int32),
                "gen": int(rng.choice(GEN_CHOICES)),
            }
        )
    return reqs


def _mode_engine(
    params, cfg, overlap, decode_steps, mesh=None, page_size=0, tracer=None,
):
    """One warmed-up engine in the requested dispatch mode (sync k=1 is
    byte-for-byte the pre-ISSUE-12 loop — the before side of the bench).
    ``mesh`` switches to the SHARDED executors (ISSUE 13) on that mesh;
    ``page_size`` > 0 to the paged flavor; ``tracer`` overrides the
    engine's default-on EngineTracer (the --trace bench passes a
    NullTracer for its tracer-off side)."""
    kwargs = dict(
        num_slots=NUM_SLOTS, max_len=MAX_LEN, seed=SEED,
        decode_steps=decode_steps,
    )
    if mesh is not None:
        from tpu_nexus.serving import (
            ShardedModelExecutor,
            ShardedPagedModelExecutor,
        )

        if page_size:
            executor = ShardedPagedModelExecutor(
                params, cfg, mesh=mesh, page_size=page_size, **kwargs
            )
        else:
            executor = ShardedModelExecutor(params, cfg, mesh=mesh, **kwargs)
    elif page_size:
        executor = PagedModelExecutor(params, cfg, page_size=page_size, **kwargs)
    else:
        executor = ModelExecutor(params, cfg, **kwargs)
    engine = ServingEngine(executor, overlap=overlap, tracer=tracer)
    # warmup: one request per prefill bucket in play + the decode dispatch
    for width in (PROMPT_RANGE[0], PROMPT_RANGE[1]):
        engine.submit(np.arange(1, width + 1, dtype=np.int32), 2)
    engine.run_until_drained()
    return engine


def run_engine_offline(
    params, cfg, requests, overlap=False, decode_steps=1, repeats=1,
    mesh=None, page_size=0, tracer=None,
):
    """All requests queued at t=0: pure completed-tokens/s.  Returns the
    per-request output streams too, so the overlap bench can assert the
    new modes token-identical to the synchronous oracle.  ``repeats``
    re-runs the measured pass and keeps the best timing (the overlap
    bench's sub-second passes are noisy on a shared CI box); outputs of
    EVERY repeat go into the identity check."""
    engine = _mode_engine(params, cfg, overlap, decode_steps, mesh, page_size, tracer)
    best = None
    outputs = {}
    for rep in range(repeats):
        engine.metrics = ServingMetrics()
        n_warm = len(engine.retired)
        steps_before = engine.steps
        t0 = time.perf_counter()
        for i, r in enumerate(requests):
            engine.submit(r["prompt"], r["gen"], request_id=f"off{rep}-{i}")
        engine.run_until_drained()
        elapsed = time.perf_counter() - t0
        done = engine.retired[n_warm:]
        tokens = sum(
            len(r.output_tokens) for r in done if r.state == RequestState.FINISHED
        )
        # keyed by the FULL rep-qualified id: every repeat participates in
        # the cross-mode identity check (a divergence in any repeat —
        # e.g. state carried over the reused engine — must fail the
        # assert, not be overwritten by a clean later repeat)
        outputs.update((r.request_id, list(r.output_tokens)) for r in done)
        run = (tokens, elapsed, engine.steps - steps_before)
        if best is None or tokens / elapsed > best[0] / best[1]:
            best = run
    return (*best, outputs)


def run_engine_poisson(params, cfg, requests, rng, overlap=False, decode_steps=1):
    """Open-loop Poisson arrivals: the latency SLO view (TTFT/TPOT)."""
    engine = _mode_engine(params, cfg, overlap, decode_steps)
    engine.metrics = metrics = ServingMetrics()

    offsets = np.cumsum(rng.exponential(1.0 / ARRIVAL_RPS, size=len(requests)))
    t0 = time.perf_counter()
    idx = 0
    while idx < len(requests) or engine.has_work:
        now = time.perf_counter() - t0
        while idx < len(requests) and offsets[idx] <= now:
            engine.submit(requests[idx]["prompt"], requests[idx]["gen"], request_id=f"poi-{idx}")
            idx += 1
        if engine.has_work:
            engine.step()
        elif idx < len(requests):
            time.sleep(min(0.001, offsets[idx] - now))
    return metrics.summary()


def run_lockstep(params, cfg, requests):
    """The run_serving discipline: rounds of NUM_SLOTS requests, each
    round decoding to its LONGEST request's budget (prompts right-padded
    with per-row prompt_lengths — the ragged ``generate`` contract).
    Useful tokens = what each request actually asked for; the overshoot
    is the waste this bench prices."""
    width = PROMPT_RANGE[1]
    gen_fns = {}
    for t in sorted({g for g in GEN_CHOICES}):
        gen_fns[t] = jax.jit(
            functools.partial(
                generate, cfg=cfg, max_new_tokens=t, max_len=width + t
            )
        )
    rounds = [requests[i : i + NUM_SLOTS] for i in range(0, len(requests), NUM_SLOTS)]

    def batch_of(round_reqs):
        padded = np.zeros((NUM_SLOTS, width), np.int32)
        lens = np.ones(NUM_SLOTS, np.int32)  # pad rows decode garbage, uncounted
        for j, r in enumerate(round_reqs):
            padded[j, : len(r["prompt"])] = r["prompt"]
            lens[j] = len(r["prompt"])
        return jnp.asarray(padded), jnp.asarray(lens)

    # warmup every distinct round shape (compile excluded, like run_serving)
    for t in gen_fns:
        p, l = batch_of(rounds[0])
        jax.block_until_ready(gen_fns[t](params, p, prompt_lengths=l))

    t0 = time.perf_counter()
    useful = 0
    for round_reqs in rounds:
        t = max(r["gen"] for r in round_reqs)
        p, l = batch_of(round_reqs)
        jax.block_until_ready(gen_fns[t](params, p, prompt_lengths=l))
        useful += sum(r["gen"] for r in round_reqs)
    return useful, time.perf_counter() - t0


# -- shared-prefix workload (ISSUE 6) ------------------------------------------

FANOUT = int(os.environ.get("NEXUS_PREFIX_FANOUT", "48"))
SHARED_LEN = int(os.environ.get("NEXUS_PREFIX_SHARED_LEN", "48"))
TAIL_LEN = 4
PREFIX_GEN = 8
PAGE_SIZE = int(os.environ.get("NEXUS_PREFIX_PAGE", "4"))
PREFIX_MAX_LEN = SHARED_LEN + TAIL_LEN + PREFIX_GEN


def make_prefix_requests(rng):
    """One system prompt, ``FANOUT`` users: every prompt is the shared
    prefix + a short unique tail (tokens 256.. so warmup prompts, drawn
    below 256, can never alias a measured prefix)."""
    shared = rng.integers(256, 512, size=SHARED_LEN).astype(np.int32)
    return [
        np.concatenate([shared, rng.integers(256, 512, size=TAIL_LEN).astype(np.int32)])
        for _ in range(FANOUT)
    ]


def _drain_tracking_peak(engine, requests):
    """Submit everything at t=0, pump to drain, return (useful_tokens,
    elapsed_s, peak concurrently-resident requests)."""
    t0 = time.perf_counter()
    for i, prompt in enumerate(requests):
        engine.submit(prompt, PREFIX_GEN, request_id=f"fan-{i}")
    peak = 0
    steps = 0
    while engine.has_work:
        engine.step()
        steps += 1
        peak = max(peak, engine.slots.used_count)
        if steps > 100_000:
            raise RuntimeError("shared-prefix bench failed to drain")
    elapsed = time.perf_counter() - t0
    tokens = sum(
        len(r.output_tokens)
        for r in engine.retired
        if r.state == RequestState.FINISHED and r.request_id.startswith("fan-")
    )
    return tokens, elapsed, peak


def run_prefix_paged(params, cfg, requests):
    """Paged engine at the SAME KV HBM budget as the slot baseline:
    ``NUM_SLOTS × max_len`` cache rows re-cut into blocks.  Decode lanes
    are raised to the block-pool's theoretical concurrency — lanes are
    host bookkeeping + batch rows, not KV memory."""
    budget_rows = NUM_SLOTS * PREFIX_MAX_LEN
    num_blocks = 1 + budget_rows // PAGE_SIZE
    lanes = int(os.environ.get("NEXUS_PREFIX_LANES", str(4 * NUM_SLOTS)))
    executor = PagedModelExecutor(
        params, cfg, num_slots=lanes, max_len=PREFIX_MAX_LEN,
        page_size=PAGE_SIZE, num_blocks=num_blocks, seed=SEED,
    )
    engine = ServingEngine(executor)
    # warmup compiles: full-prefill bucket, extend bucket (prefix hit),
    # COW copy, decode step — warmup tokens < 256, measured >= 256, so no
    # warmup prefix can leak into the measured lookups
    warm = np.arange(1, SHARED_LEN + TAIL_LEN + 1, dtype=np.int32)
    engine.submit(warm, 2, request_id="warm-full")
    engine.run_until_drained()
    engine.submit(np.concatenate([warm[:-1], [255]]).astype(np.int32), 2, request_id="warm-ext")
    engine.run_until_drained()
    engine.metrics = metrics = ServingMetrics()
    prefilled_before = executor.prefilled_tokens

    tokens, elapsed, peak = _drain_tracking_peak(engine, requests)
    summary = metrics.summary()
    return {
        "tokens": tokens,
        "elapsed_s": elapsed,
        "peak_concurrent": peak,
        "prefilled_tokens": executor.prefilled_tokens - prefilled_before,
        "prefix_hits": summary["prefix_hits"],
        "prefix_shared_tokens": summary["prefix_shared_tokens"],
        "blocks_cow": summary["blocks_cow"],
        "num_blocks": num_blocks,
        "page_size": PAGE_SIZE,
        "lanes": lanes,
    }


def run_prefix_slot_granular(params, cfg, requests):
    """The baseline: same workload, same KV bytes, whole-row slots — the
    shared prompt is prefilled and stored once PER REQUEST."""
    executor = ModelExecutor(
        params, cfg, num_slots=NUM_SLOTS, max_len=PREFIX_MAX_LEN, seed=SEED
    )
    engine = ServingEngine(executor)
    warm = np.arange(1, SHARED_LEN + TAIL_LEN + 1, dtype=np.int32)
    engine.submit(warm, 2, request_id="warm-full")
    engine.run_until_drained()
    engine.metrics = ServingMetrics()

    tokens, elapsed, peak = _drain_tracking_peak(engine, requests)
    return {
        "tokens": tokens,
        "elapsed_s": elapsed,
        "peak_concurrent": peak,
        "prefilled_tokens": sum(len(p) for p in requests),
        "slots": NUM_SLOTS,
    }


# -- speculative decoding workload (ISSUE 11) ----------------------------------

SPEC_K = int(os.environ.get("NEXUS_SPEC_BENCH_K", "2"))
SPEC_GEN = int(os.environ.get("NEXUS_SPEC_BENCH_GEN", "288"))
SPEC_REQUESTS = int(os.environ.get("NEXUS_SPEC_BENCH_REQUESTS", "16"))


def make_spec_requests(rng):
    """Repetitive-suffix traffic: each prompt is a short unique head + a
    motif repeated 4x.  The motif pushes the (deterministic, greedy)
    generation into repeating cycles the prompt-lookup drafter can
    predict; the honest acceptance rate in the artifact says how often it
    actually did."""
    prompts = []
    for _ in range(SPEC_REQUESTS):
        head = rng.integers(1, 256, size=int(rng.integers(2, 7))).astype(np.int32)
        motif = rng.integers(1, 256, size=int(rng.integers(3, 7))).astype(np.int32)
        prompts.append(np.concatenate([head] + [motif] * 4)[:40])
    return prompts


def run_spec_engine(params, cfg, requests, max_len, spec_k):
    """One engine pass over the request set; spec_k=0 is the baseline.
    Same slots, same jitted model fns, same admission order."""
    from tpu_nexus.serving import NGramDrafter

    executor = ModelExecutor(
        params, cfg, num_slots=NUM_SLOTS, max_len=max_len, seed=SEED
    )
    drafter = NGramDrafter(NUM_SLOTS) if spec_k else None
    engine = ServingEngine(executor, spec_k=spec_k, drafter=drafter)
    for width in (8, 32):  # warmup: prefill buckets + decode/verify jits
        engine.submit(np.arange(1, width + 1, dtype=np.int32), 2)
    engine.run_until_drained()
    engine.metrics = metrics = ServingMetrics()
    n_warm = len(engine.retired)

    t0 = time.perf_counter()
    for i, prompt in enumerate(requests):
        engine.submit(prompt, SPEC_GEN, request_id=f"spec-{i}")
    engine.run_until_drained(max_steps=400_000)
    elapsed = time.perf_counter() - t0
    tokens = sum(
        len(r.output_tokens)
        for r in engine.retired[n_warm:]
        if r.state == RequestState.FINISHED
    )
    summary = metrics.summary()
    return {
        "tokens": tokens,
        "elapsed_s": elapsed,
        "engine_steps": engine.steps,
        "tokens_per_second": tokens / elapsed if elapsed else 0.0,
        "spec_proposed": summary["spec_proposed"],
        "spec_accepted": summary["spec_accepted"],
        "acceptance_rate": summary["spec_acceptance_rate"],
    }


def main_speculative():
    rng = np.random.default_rng(SEED)
    cfg = bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)
    requests = make_spec_requests(rng)
    max_len = max(len(p) for p in requests) + SPEC_GEN

    base = run_spec_engine(params, cfg, requests, max_len, 0)
    spec = run_spec_engine(params, cfg, requests, max_len, SPEC_K)
    assert spec["tokens"] == base["tokens"], "spec-on must complete the same work"

    ratio = (
        spec["tokens_per_second"] / base["tokens_per_second"]
        if base["tokens_per_second"]
        else 0.0
    )
    result = {
        "metric": "speculative_tokens_per_second_ratio",
        "value": round(ratio, 3),
        "unit": "x_tokens_per_second_vs_spec_off",
        "spec_k": SPEC_K,
        "drafter": "ngram",
        "acceptance_rate": round(spec["acceptance_rate"], 4),
        "workload": {
            "requests": SPEC_REQUESTS,
            "gen_tokens": SPEC_GEN,
            "prompt": "head(2-6) + motif(3-6) x 4, repetitive-suffix",
            "slots": NUM_SLOTS,
        },
        "spec_on": {
            k: (round(v, 4) if isinstance(v, float) else v) for k, v in spec.items()
        },
        "spec_off": {
            k: (round(v, 4) if isinstance(v, float) else v) for k, v in base.items()
        },
        "note": (
            "CPU bench: a q_len=k+1 verify pays ~linear compute, so this "
            "ratio is the floor; on TPU decode is bandwidth-bound and the "
            "verify is nearly free, scaling the win toward 1 + accepted/step"
        ),
        "seed": SEED,
        "model": "llama-bench-4L-h256",
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_SERVING_SPEC_OUT", "BENCH_SERVING_SPEC_r08.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


def main_shared_prefix():
    rng = np.random.default_rng(SEED)
    cfg = bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)
    requests = make_prefix_requests(rng)

    paged = run_prefix_paged(params, cfg, requests)
    slot = run_prefix_slot_granular(params, cfg, requests)

    paged_tps = paged["tokens"] / paged["elapsed_s"] if paged["elapsed_s"] else 0.0
    slot_tps = slot["tokens"] / slot["elapsed_s"] if slot["elapsed_s"] else 0.0
    result = {
        "metric": "shared_prefix_concurrent_capacity_ratio",
        # the headline: concurrent requests the SAME KV HBM hosts
        "value": round(paged["peak_concurrent"] / max(1, slot["peak_concurrent"]), 3),
        "unit": "x_concurrent_requests_at_equal_kv_hbm",
        "kv_budget_rows": NUM_SLOTS * PREFIX_MAX_LEN,
        "workload": {
            "fanout": FANOUT,
            "shared_prompt_len": SHARED_LEN,
            "tail_len": TAIL_LEN,
            "gen_tokens": PREFIX_GEN,
            "max_len": PREFIX_MAX_LEN,
        },
        "paged": {
            **{k: (round(v, 4) if isinstance(v, float) else v) for k, v in paged.items()},
            "tokens_per_second": round(paged_tps, 2),
        },
        "slot_granular": {
            **{k: (round(v, 4) if isinstance(v, float) else v) for k, v in slot.items()},
            "tokens_per_second": round(slot_tps, 2),
        },
        "speedup_tokens_per_second": round(paged_tps / slot_tps, 3) if slot_tps else None,
        "prefill_reduction": (
            round(slot["prefilled_tokens"] / paged["prefilled_tokens"], 3)
            if paged["prefilled_tokens"]
            else None
        ),
        "seed": SEED,
        "model": "llama-bench-4L-h256",
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_SERVING_PREFIX_OUT", "BENCH_SERVING_PREFIX_r07.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


# -- tensor-parallel sharded serving workload (ISSUE 13) -----------------------

MESH_PAGE = int(os.environ.get("NEXUS_MESH_BENCH_PAGE", "4"))


def mesh_bench_model() -> LlamaConfig:
    """:func:`bench_model` in f32 with tp-divisible heads: identity is the
    artifact's headline, and TP psum reordering resolves exact bf16
    argmax ties differently (the documented near-tie caveat) — f32 keeps
    the cross-mode assert exact instead of probabilistic."""
    return LlamaConfig(
        vocab_size=512, hidden=256, n_layers=4, n_heads=8, n_kv_heads=8,
        head_dim=32, intermediate=512, max_seq_len=2 * MAX_LEN, remat=False,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )


def main_mesh(mesh_spec: str):
    """``--mesh tp=N``: the same offline request set through the
    single-chip engine and the SHARDED executors (contiguous + paged) on
    an N-way mesh, outputs asserted token-identical across all modes.
    The honest number on a virtual CPU mesh is the parity + the dispatch
    counts — the "devices" timeshare the host cores, so elapsed prices
    GSPMD partition overhead, not TP speedup (see module docstring)."""
    from tpu_nexus.serving.sharded import build_serve_mesh, parse_serve_mesh

    axes = parse_serve_mesh(mesh_spec)
    mesh = build_serve_mesh(axes)
    rng = np.random.default_rng(SEED)
    cfg = mesh_bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)
    requests = make_requests(rng)

    modes = {
        "single_chip": dict(),
        "sharded": dict(mesh=mesh),
        "single_chip_paged": dict(page_size=MESH_PAGE),
        "sharded_paged": dict(mesh=mesh, page_size=MESH_PAGE),
    }
    rows = {}
    outputs = {}
    for name, kw in modes.items():
        tokens, elapsed, steps, outs = run_engine_offline(
            params, cfg, requests, repeats=2, **kw
        )
        rows[name] = {
            "tokens": tokens,
            "elapsed_s": round(elapsed, 4),
            "engine_steps": steps,
            "tokens_per_second": round(tokens / elapsed, 2) if elapsed else 0.0,
        }
        outputs[name] = outs
    for name in ("sharded", "single_chip_paged", "sharded_paged"):
        assert outputs[name] == outputs["single_chip"], (
            f"{name} outputs diverge from the single-chip engine"
        )
    # the dispatch-count row: sharding must not change the engine's step
    # accounting — same admissions, same decode iterations
    assert (
        rows["sharded"]["engine_steps"] == rows["single_chip"]["engine_steps"]
    ), "sharding changed the engine's dispatch count"

    base = rows["single_chip"]["tokens_per_second"]
    result = {
        "metric": "sharded_engine_tokens_per_second_ratio",
        "value": (
            round(rows["sharded"]["tokens_per_second"] / base, 3) if base else 0.0
        ),
        "unit": "x_tokens_per_second_vs_single_chip",
        "mesh": axes,
        "devices": int(mesh.devices.size),
        "token_identical": True,  # asserted above, all four modes
        "dispatch_parity": True,  # asserted above
        "paged_ratio": (
            round(
                rows["sharded_paged"]["tokens_per_second"]
                / max(rows["single_chip_paged"]["tokens_per_second"], 1e-9),
                3,
            )
        ),
        "modes": rows,
        "workload": {
            "requests": N_REQUESTS,
            "slots": NUM_SLOTS,
            "prompt_len_range": list(PROMPT_RANGE),
            "gen_tokens_choices": list(GEN_CHOICES),
            "page_size": MESH_PAGE,
            "best_of": 2,
        },
        "note": (
            "virtual CPU mesh: the N 'devices' timeshare the same host "
            "cores, so the ratio prices GSPMD partition/dispatch overhead "
            "— a TP SPEEDUP is not measurable here (the r9 precedent: "
            "tiny-scale CPU benches measure dispatch).  The artifact's "
            "value is the token-identity + dispatch-count parity rows: "
            "the sharded engine does the same scheduling work and emits "
            "the same tokens.  f32 model: TP psum reordering flips exact "
            "bf16 argmax ties (docs/SERVING.md)."
        ),
        "seed": SEED,
        "model": "llama-bench-4L-h256-f32 (kv_heads=8, tp-divisible)",
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_SERVING_TP_OUT", "BENCH_SERVING_TP_r10.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


# -- overlapped dispatch workload (ISSUE 12) -----------------------------------

OVERLAP_DECODE_STEPS = int(os.environ.get("NEXUS_OVERLAP_BENCH_STEPS", "8"))
OVERLAP_REQUESTS = int(os.environ.get("NEXUS_OVERLAP_BENCH_REQUESTS", "144"))


def overlap_bench_model() -> LlamaConfig:
    """DELIBERATELY dispatch-bound (the opposite of :func:`bench_model`'s
    sizing note): the host-tax bench must measure the thing the refactor
    removes, so the per-step device compute is made SMALL relative to the
    fixed per-dispatch framework cost (~0.5 ms on this CPU backend).  On
    real serving hardware this regime is the NORM, not a trick: a TPU
    decode step for a small model is tens of microseconds of device time
    behind the same fixed host dispatch cost."""
    return LlamaConfig(
        vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=16, intermediate=128, max_seq_len=2 * MAX_LEN, remat=False,
    )


def main_overlap():
    """``--overlap`` / ``--decode-steps``: the host-tax bench.  The SAME
    mixed-length request set through the byte-identical synchronous k=1
    engine (before) and the three new modes — overlapped dispatch alone,
    in-jit multi-step decode alone, and both composed — with greedy
    outputs asserted token-identical across ALL modes, so any speedup is
    pure dispatch accounting, not different work.  TTFT/TPOT ride the
    existing Poisson driver for the before and after modes.

    Honest framing: on this CPU backend the "device" executes on the host
    cores, so DEFERRED READBACK alone cannot win (there is no independent
    device to overlap with — expect overlap ~<= 1x here; its payoff needs
    genuinely asynchronous hardware).  What CPU CAN measure is the
    k-step scan amortizing the fixed per-dispatch cost k-fold — the same
    fixed cost a TPU host pays per step — so the multistep ratios below
    are the honest CPU-observable floor of the host-tax removal."""
    rng = np.random.default_rng(SEED)
    cfg = overlap_bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)
    requests = make_requests(rng, n=OVERLAP_REQUESTS)

    modes = {
        "sync": dict(overlap=False, decode_steps=1),
        "overlap": dict(overlap=True, decode_steps=1),
        "multistep": dict(overlap=False, decode_steps=OVERLAP_DECODE_STEPS),
        "overlap_multistep": dict(overlap=True, decode_steps=OVERLAP_DECODE_STEPS),
    }
    offline = {}
    outputs = {}
    for name, kw in modes.items():
        tokens, elapsed, steps, outs = run_engine_offline(
            params, cfg, requests, repeats=3, **kw
        )
        offline[name] = {
            "tokens": tokens,
            "elapsed_s": round(elapsed, 4),
            "engine_steps": steps,
            "tokens_per_second": round(tokens / elapsed, 2) if elapsed else 0.0,
        }
        outputs[name] = outs
    for name in ("overlap", "multistep", "overlap_multistep"):
        assert outputs[name] == outputs["sync"], (
            f"{name} outputs diverge from the synchronous oracle"
        )

    poisson = {
        name: run_engine_poisson(
            params, cfg, requests, np.random.default_rng(SEED + 1), **modes[name]
        )
        for name in ("sync", "overlap_multistep")
    }
    base_tps = offline["sync"]["tokens_per_second"]

    def ratio(name):
        return (
            round(offline[name]["tokens_per_second"] / base_tps, 3)
            if base_tps
            else 0.0
        )

    best_mode = max(
        ("overlap", "multistep", "overlap_multistep"), key=ratio
    )
    result = {
        "metric": "overlapped_engine_tokens_per_second_ratio",
        # the headline: the best NEW mode vs the synchronous loop.  On
        # this CPU backend that is multistep (see note); on async
        # hardware the composition is the expected winner.
        "value": ratio(best_mode),
        "best_mode": best_mode,
        "unit": "x_tokens_per_second_vs_sync_engine",
        "decode_steps": OVERLAP_DECODE_STEPS,
        "overlap_only_ratio": ratio("overlap"),
        "multistep_only_ratio": ratio("multistep"),
        "overlap_multistep_ratio": ratio("overlap_multistep"),
        "token_identical": True,  # asserted above, across all four modes
        "offline": offline,
        "poisson": {
            name: {
                "arrival_rps": ARRIVAL_RPS,
                "ttft_p50_s": round(p["ttft_p50_s"], 5),
                "ttft_p99_s": round(p["ttft_p99_s"], 5),
                "tpot_p50_s": round(p["tpot_p50_s"], 5),
                "tpot_p99_s": round(p["tpot_p99_s"], 5),
            }
            for name, p in poisson.items()
        },
        "workload": {
            "requests": OVERLAP_REQUESTS,
            "slots": NUM_SLOTS,
            "prompt_len_range": list(PROMPT_RANGE),
            "gen_tokens_choices": list(GEN_CHOICES),
            "best_of": 3,
        },
        "note": (
            "dispatch-bound CPU bench (model sized so fixed per-dispatch "
            "cost dominates device compute — the normal TPU serving "
            "regime).  The k-step in-jit scan amortizes that fixed cost "
            "k-fold: the CPU-observable win.  Deferred readback (overlap) "
            "alone CANNOT win on CPU — the 'device' runs on the host "
            "cores, so there is nothing independent to overlap with; its "
            "~0.7-0.8x here prices the pipeline bookkeeping + one-step-"
            "late slot refill, and its payoff needs genuinely async "
            "hardware.  Composed, overlap costs a slice of the multistep "
            "win on CPU for the same reason."
        ),
        "seed": SEED,
        "model": "llama-overlap-2L-h64 (dispatch-bound by design)",
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_SERVING_ASYNC_OUT", "BENCH_SERVING_ASYNC_r09.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


# -- tracer overhead workload (ISSUE 14) ---------------------------------------


def main_trace():
    """``--trace``: the observability tax, measured.  The SAME mixed-length
    request set through the engine with the default-on EngineTracer and
    with the NullTracer, outputs asserted token-identical (tracing must
    not change token streams — the structural half of the guarantee; the
    real-model identity matrices run tracer-on as the behavioral half).

    Two regimes, honestly separated: the standard bench model
    (compute-bound at this scale — the normal serving regime, where the
    tracer's per-step host appends hide behind device compute) and the
    DELIBERATELY dispatch-bound overlap-bench model (the worst case: host
    work IS the bottleneck, so every tracer append is on the critical
    path).  The acceptance bar (≤ 2% tokens/s) applies to the standard
    model; the dispatch-bound row is the stress ceiling, reported so the
    overhead claim cannot hide behind a compute-bound denominator."""
    rng = np.random.default_rng(SEED)
    requests = make_requests(rng)
    from tpu_nexus.serving import NullTracer

    repeats = int(os.environ.get("NEXUS_TRACE_BENCH_REPEATS", "5"))

    # host-only microbench FIRST, before any jax model work: a
    # deterministic numpy fake executor (no XLA, no thread-pool noise)
    # isolates the tracer's per-step host cost EXACTLY — and running it
    # on a small heap matters, because the tracer's allocations trigger
    # gen-2 GC passes whose cost scales with everything else alive in
    # the process (measured 305us/step when this ran AFTER the model
    # benches vs ~14us/step before them — the latter is the honest
    # per-step cost, the former a lesson in measurement hygiene).
    class _HostFake:
        def __init__(self, num_slots, max_len):
            self.num_slots, self.max_len = num_slots, max_len

        def begin(self, slot, prompt):
            return int(prompt[-1]) + 1

        def step(self, tokens, cursors):
            return np.asarray(tokens) + 1

    rng_host = np.random.default_rng(SEED)
    host_requests = make_requests(rng_host)
    host = {}
    for side in ("tracer_on", "tracer_off"):
        tracer = None if side == "tracer_on" else NullTracer()
        engine = ServingEngine(_HostFake(NUM_SLOTS, MAX_LEN), tracer=tracer)
        for r in host_requests:  # warm the allocator paths
            engine.submit(r["prompt"], min(r["gen"], 2))
        engine.run_until_drained()
        t0 = time.perf_counter()
        steps_before = engine.steps
        for rep in range(3):
            for i, r in enumerate(host_requests):
                engine.submit(r["prompt"], r["gen"], request_id=f"h{rep}-{i}")
            engine.run_until_drained()
        host[side] = {
            "elapsed_s": round(time.perf_counter() - t0, 4),
            "engine_steps": engine.steps - steps_before,
        }
    host_us_per_step = {
        side: round(1e6 * v["elapsed_s"] / v["engine_steps"], 2)
        for side, v in host.items()
    }
    tracer_cost_us = round(
        host_us_per_step["tracer_on"] - host_us_per_step["tracer_off"], 2
    )

    regimes = {
        "compute_bound": (bench_model(), "llama-bench-4L-h256"),
        "dispatch_bound": (overlap_bench_model(), "llama-overlap-2L-h64"),
    }
    rows = {}
    for regime, (cfg, model_name) in regimes.items():
        params = llama_init(jax.random.PRNGKey(SEED), cfg)
        # one persistent warmed engine PER SIDE, measured passes strictly
        # INTERLEAVED (on, off, on, off, ...): the tracer's per-step cost
        # is tens of microseconds while XLA-CPU thread-pool drift over a
        # multi-second bench is easily ±10% — back-to-back pass pairs see
        # the same box state, so best-of-N per side cancels the drift a
        # sequential A-then-B run bakes into the ratio
        engines = {
            "tracer_on": _mode_engine(params, cfg, False, 1, tracer=None),
            "tracer_off": _mode_engine(params, cfg, False, 1, tracer=NullTracer()),
        }
        best = {}
        outputs = {"tracer_on": {}, "tracer_off": {}}
        pair_tps = {"tracer_on": [], "tracer_off": []}
        for rep in range(repeats):
            for side, engine in engines.items():
                engine.metrics = ServingMetrics()
                n_warm = len(engine.retired)
                steps_before = engine.steps
                t0 = time.perf_counter()
                for i, r in enumerate(requests):
                    engine.submit(r["prompt"], r["gen"], request_id=f"tr{rep}-{i}")
                engine.run_until_drained()
                elapsed = time.perf_counter() - t0
                done = engine.retired[n_warm:]
                tokens = sum(
                    len(r.output_tokens)
                    for r in done
                    if r.state == RequestState.FINISHED
                )
                outputs[side].update(
                    (f"{rep}-{r.request_id}", list(r.output_tokens)) for r in done
                )
                pair_tps[side].append(tokens / elapsed if elapsed else 0.0)
                run = (tokens, elapsed, engine.steps - steps_before)
                if side not in best or tokens / elapsed > best[side][0] / best[side][1]:
                    best[side] = run
        assert outputs["tracer_on"] == outputs["tracer_off"], (
            f"{regime}: tracer changed token streams"
        )
        sides = {
            side: {
                "tokens": tokens,
                "elapsed_s": round(elapsed, 4),
                "engine_steps": steps,
                "tokens_per_second": round(tokens / elapsed, 2) if elapsed else 0.0,
            }
            for side, (tokens, elapsed, steps) in best.items()
        }
        # the headline statistic: MEDIAN of per-pair ratios — each pair
        # ran back-to-back on the same box state, so the ratio cancels
        # drift a best-of comparison (max over different moments) re-adds
        pair_ratios = sorted(
            on_tps / off_tps
            for on_tps, off_tps in zip(pair_tps["tracer_on"], pair_tps["tracer_off"])
            if off_tps
        )
        ratio = pair_ratios[len(pair_ratios) // 2] if pair_ratios else 0.0
        # per-step duration from the tracer-off side: the denominator the
        # deterministic host-only tracer cost is priced against below
        off_best = best["tracer_off"]
        step_us = 1e6 * off_best[1] / off_best[2] if off_best[2] else 0.0
        rows[regime] = {
            "model": model_name,
            **sides,
            "step_us_tracer_off": round(step_us, 1),
            "pair_ratios_on_vs_off": [round(r, 4) for r in pair_ratios],
            "tokens_per_second_ratio_on_vs_off": round(ratio, 4),
            "ratio_overhead_pct": round((1.0 - ratio) * 100.0, 2),
        }
    # the headline: the DETERMINISTIC tracer cost (host-only microbench)
    # priced against each regime's measured step duration — the worst
    # regime is the bound.  The interleaved model-engine ratios scatter
    # ±8% around 1.0 per pair on this box (XLA-CPU pass-to-pass variance;
    # verified with GC disabled), so a median ratio CANNOT resolve a
    # sub-1% effect — it rides in the rows as corroboration ("within
    # noise of 1.0"), never as the claim.
    for row in rows.values():
        row["bound_overhead_pct"] = (
            round(100.0 * tracer_cost_us / row["step_us_tracer_off"], 2)
            if row["step_us_tracer_off"]
            else 0.0
        )
    worst = max(rows.values(), key=lambda r: r["bound_overhead_pct"])
    result = {
        "metric": "tracer_overhead_tokens_per_second_pct",
        "value": worst["bound_overhead_pct"],
        "value_basis": (
            "deterministic host-only tracer cost / measured per-step "
            "duration, worst regime"
        ),
        "host_only_us_per_engine_step": {
            **host_us_per_step,
            "tracer_cost_us_per_step": tracer_cost_us,
        },
        "unit": "pct_tokens_per_second_lost_tracer_on_vs_off",
        "target_pct": 2.0,
        "regimes": rows,
        "token_identical": True,  # asserted above, both regimes
        "workload": {
            "requests": N_REQUESTS,
            "slots": NUM_SLOTS,
            "prompt_len_range": list(PROMPT_RANGE),
            "gen_tokens_choices": list(GEN_CHOICES),
            "best_of": repeats,
            "interleaved": True,
        },
        "note": (
            "tracer-on = the DEFAULT engine configuration (span timelines "
            "on every request + one flight-recorder ring append per step); "
            "tracer-off = NullTracer.  The claim rests on the "
            "deterministic measurement: host_only_us_per_engine_step "
            "isolates the tracer's per-step host cost with no XLA in the "
            "loop, and `value` prices it against the WORST regime's "
            "measured step duration.  The interleaved model-engine pair "
            "ratios are corroboration only: per-pass XLA-CPU variance on "
            "this box is ±8% (verified with GC disabled), so their "
            "medians scatter around 1.0 and cannot resolve a sub-1% "
            "effect — treat ratio_overhead_pct as noise-bounded, and "
            "distrust any sequential A-then-B comparison entirely (one "
            "measured the tracer 12% FASTER)."
        ),
        "seed": SEED,
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_SERVING_TRACE_OUT", "BENCH_SERVING_TRACE_r11.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


def main_slo():
    """``--slo``: the pressure plane's observation tax, measured (ISSUE
    15; the --trace bench's r11 methodology).  The SAME mixed-length
    request set through the engine with a per-step
    ``load_snapshot()`` + ``SloMonitor.observe()`` and without, outputs
    asserted token-identical (observation reads materialized host state
    only — the structural half; the real-model identity matrices in
    tests/test_loadstats.py are the behavioral half).

    Per the PERF.md r11 lesson: the headline is the DETERMINISTIC
    host-only snapshot+observe cost (numpy fake engine, measured FIRST in
    the process before the jax heap exists) priced against each regime's
    measured per-step duration; the interleaved model-engine pair ratios
    ride as noise-bounded corroboration only.  Bar: ≤ 2% of tokens/s in
    the worst regime.  Note the production cadence is one observation per
    supervisor RECONCILE (~1/s), not per engine step — per-step here is
    the conservative ceiling."""
    from tpu_nexus.serving import FleetSnapshot, SloMonitor, SloTargets

    rng = np.random.default_rng(SEED)
    requests = make_requests(rng)
    repeats = int(os.environ.get("NEXUS_SLO_BENCH_REPEATS", "5"))

    def make_monitor():
        # tight targets so the monitor actually grades (transitions fire)
        # rather than idling down a never-violated fast path
        return SloMonitor(
            SloTargets(ttft_p99_s=1e-9, tpot_p99_s=1e-9,
                       short_window=2, long_window=8)
        )

    def drain_observed(engine, monitor):
        while engine.has_work:
            engine.step()
            if monitor is not None:
                snap = engine.load_snapshot(replica="e")
                monitor.observe(FleetSnapshot.aggregate({"e": snap}))

    # host-only microbench FIRST (small heap — the r11 GC lesson): a
    # deterministic numpy fake isolates the per-step snapshot+observe cost
    class _HostFake:
        def __init__(self, num_slots, max_len):
            self.num_slots, self.max_len = num_slots, max_len

        def begin(self, slot, prompt):
            return int(prompt[-1]) + 1

        def step(self, tokens, cursors):
            return np.asarray(tokens) + 1

    host = {}
    host_requests = make_requests(np.random.default_rng(SEED))
    for side in ("monitor_on", "monitor_off"):
        engine = ServingEngine(_HostFake(NUM_SLOTS, MAX_LEN))
        monitor = make_monitor() if side == "monitor_on" else None
        for r in host_requests:  # warm the allocator paths
            engine.submit(r["prompt"], min(r["gen"], 2))
        drain_observed(engine, monitor)
        t0 = time.perf_counter()
        steps_before = engine.steps
        for rep in range(3):
            for i, r in enumerate(host_requests):
                engine.submit(r["prompt"], r["gen"], request_id=f"h{rep}-{i}")
            drain_observed(engine, monitor)
        host[side] = {
            "elapsed_s": round(time.perf_counter() - t0, 4),
            "engine_steps": engine.steps - steps_before,
        }
    host_us_per_step = {
        side: round(1e6 * v["elapsed_s"] / v["engine_steps"], 2)
        for side, v in host.items()
    }
    monitor_cost_us = round(
        host_us_per_step["monitor_on"] - host_us_per_step["monitor_off"], 2
    )

    regimes = {
        "compute_bound": (bench_model(), "llama-bench-4L-h256"),
        "dispatch_bound": (overlap_bench_model(), "llama-overlap-2L-h64"),
    }
    rows = {}
    for regime, (cfg, model_name) in regimes.items():
        params = llama_init(jax.random.PRNGKey(SEED), cfg)
        engines = {
            "monitor_on": _mode_engine(params, cfg, False, 1),
            "monitor_off": _mode_engine(params, cfg, False, 1),
        }
        best = {}
        outputs = {"monitor_on": {}, "monitor_off": {}}
        pair_tps = {"monitor_on": [], "monitor_off": []}
        monitors = {"monitor_on": make_monitor(), "monitor_off": None}
        for rep in range(repeats):
            # interleaved pass pairs (r11 methodology): back-to-back runs
            # see the same box state, so per-pair ratios cancel the ±8%
            # XLA-CPU drift a sequential A-then-B comparison bakes in
            for side, engine in engines.items():
                engine.metrics = ServingMetrics()
                n_warm = len(engine.retired)
                steps_before = engine.steps
                t0 = time.perf_counter()
                for i, r in enumerate(requests):
                    engine.submit(r["prompt"], r["gen"], request_id=f"sl{rep}-{i}")
                drain_observed(engine, monitors[side])
                elapsed = time.perf_counter() - t0
                done = engine.retired[n_warm:]
                tokens = sum(
                    len(r.output_tokens)
                    for r in done
                    if r.state == RequestState.FINISHED
                )
                outputs[side].update(
                    (f"{rep}-{r.request_id}", list(r.output_tokens)) for r in done
                )
                pair_tps[side].append(tokens / elapsed if elapsed else 0.0)
                run = (tokens, elapsed, engine.steps - steps_before)
                if side not in best or tokens / elapsed > best[side][0] / best[side][1]:
                    best[side] = run
        assert outputs["monitor_on"] == outputs["monitor_off"], (
            f"{regime}: the SLO monitor changed token streams"
        )
        pair_ratios = sorted(
            on_tps / off_tps
            for on_tps, off_tps in zip(pair_tps["monitor_on"], pair_tps["monitor_off"])
            if off_tps
        )
        ratio = pair_ratios[len(pair_ratios) // 2] if pair_ratios else 0.0
        off_best = best["monitor_off"]
        step_us = 1e6 * off_best[1] / off_best[2] if off_best[2] else 0.0
        rows[regime] = {
            "model": model_name,
            **{
                side: {
                    "tokens": tokens,
                    "elapsed_s": round(elapsed, 4),
                    "engine_steps": steps,
                    "tokens_per_second": round(tokens / elapsed, 2) if elapsed else 0.0,
                }
                for side, (tokens, elapsed, steps) in best.items()
            },
            "step_us_monitor_off": round(step_us, 1),
            "pair_ratios_on_vs_off": [round(r, 4) for r in pair_ratios],
            "tokens_per_second_ratio_on_vs_off": round(ratio, 4),
            "ratio_overhead_pct": round((1.0 - ratio) * 100.0, 2),
            "bound_overhead_pct": (
                round(100.0 * monitor_cost_us / step_us, 2) if step_us else 0.0
            ),
        }
    worst = max(rows.values(), key=lambda r: r["bound_overhead_pct"])
    result = {
        "metric": "slo_monitor_overhead_tokens_per_second_pct",
        "value": worst["bound_overhead_pct"],
        "value_basis": (
            "deterministic host-only snapshot+observe cost / measured "
            "per-step duration, worst regime"
        ),
        "host_only_us_per_engine_step": {
            **host_us_per_step,
            "monitor_cost_us_per_step": monitor_cost_us,
        },
        "unit": "pct_tokens_per_second_lost_monitor_on_vs_off",
        "target_pct": 2.0,
        "regimes": rows,
        "token_identical": True,  # asserted above, both regimes
        "observation_cadence": "per engine step (conservative ceiling; production cadence is per supervisor reconcile)",
        "workload": {
            "requests": N_REQUESTS,
            "slots": NUM_SLOTS,
            "prompt_len_range": list(PROMPT_RANGE),
            "gen_tokens_choices": list(GEN_CHOICES),
            "best_of": repeats,
            "interleaved": True,
        },
        "note": (
            "monitor-on = ServingEngine.load_snapshot() + "
            "SloMonitor.observe() after EVERY engine step with targets "
            "tight enough that every observation violates (the grading "
            "path, not the idle path); monitor-off = the plain loop.  The "
            "claim rests on the deterministic host-only measurement per "
            "the r11 tracer methodology; interleaved pair ratios are "
            "noise-bounded corroboration only (±8%/pass XLA-CPU variance "
            "on this box class)."
        ),
        "seed": SEED,
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_SERVING_SLO_OUT", "BENCH_SERVING_SLO_r12.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


# -- fleet routing workload (ISSUE 19) -----------------------------------------

FLEET_REPLICAS = int(os.environ.get("NEXUS_FLEET_REPLICAS", "3"))
FLEET_WEAK_SLOTS = int(os.environ.get("NEXUS_FLEET_WEAK_SLOTS", "2"))
FLEET_REQUESTS = int(os.environ.get("NEXUS_FLEET_REQUESTS", str(2 * N_REQUESTS)))
FLEET_TTFT_SLO_S = float(os.environ.get("NEXUS_FLEET_TTFT_SLO_S", "0.3"))
FLEET_TPOT_SLO_S = float(os.environ.get("NEXUS_FLEET_TPOT_SLO_S", "0.08"))


def _skewed_offsets(rng, n):
    """Cumulative Poisson arrival offsets with the rate DOUBLED over the
    middle third of the request stream: the burst piles backlog onto
    whichever replica the router is feeding right then, which is exactly
    when blind rotation and load-ranked admission diverge."""
    rates = np.full(n, ARRIVAL_RPS)
    rates[n // 3 : 2 * n // 3] *= 2.0
    return np.cumsum(rng.exponential(1.0 / rates))


def _fleet_of_engines(params, cfg, policy, metrics=None):
    """FLEET_REPLICAS warmed-up contiguous engines behind one router; the
    LAST replica is WEAK (FLEET_WEAK_SLOTS slots, bounded queue) — the
    capacity skew round-robin cannot see and the load scorer can.  The
    strong replicas keep unbounded queues, so fleet-wide exhaustion never
    sheds: every request finishes under BOTH policies and the token-
    identity assert covers the full set."""
    from tpu_nexus.serving import FifoScheduler, SchedulerConfig, ServingFleet

    fleet = ServingFleet(policy=policy, metrics=metrics)
    for i in range(FLEET_REPLICAS):
        weak = i == FLEET_REPLICAS - 1
        slots = FLEET_WEAK_SLOTS if weak else NUM_SLOTS
        executor = ModelExecutor(
            params, cfg, num_slots=slots, max_len=MAX_LEN, seed=SEED
        )
        # deep enough that rotation actually PARKS work behind the weak
        # replica (the realistic failure: latency rots long before a shed
        # bounces the request) yet bounded, so a sustained burst still
        # exercises the shed-and-retry hop
        scheduler = (
            FifoScheduler(SchedulerConfig(max_queue=6 * slots)) if weak else None
        )
        engine = ServingEngine(executor, scheduler=scheduler)
        for width in (PROMPT_RANGE[0], PROMPT_RANGE[1]):
            engine.submit(np.arange(1, width + 1, dtype=np.int32), 2)
        engine.run_until_drained()
        engine.metrics = ServingMetrics()
        fleet.add_replica(f"rep-{i}", engine)
    return fleet


def run_fleet_poisson(params, cfg, requests, offsets, policy):
    """One open-loop pass of the skewed arrival schedule through a fresh
    fleet under ``policy``.  Returns (summary row, per-request outputs) —
    outputs feed the cross-policy token-identity assert."""
    from tpu_nexus.core.telemetry import RecordingMetrics
    from tpu_nexus.serving import QueueFull

    metrics = RecordingMetrics()
    fleet = _fleet_of_engines(params, cfg, policy, metrics=metrics)
    t0 = time.perf_counter()
    idx = 0
    sheds = 0
    while idx < len(requests) or fleet.has_work:
        now = time.perf_counter() - t0
        while idx < len(requests) and offsets[idx] <= now:
            r = requests[idx]
            try:
                fleet.submit(r["prompt"], r["gen"], request_id=f"fl-{idx}")
            except QueueFull:
                sheds += 1  # fleet-wide exhaustion only; the client owns it
            idx += 1
        if fleet.has_work:
            fleet.tick()
        elif idx < len(requests):
            time.sleep(min(0.001, offsets[idx] - now))
    elapsed = time.perf_counter() - t0

    done = [
        r
        for r in fleet.all_retired()
        if r.request_id.startswith("fl-") and r.state == RequestState.FINISHED
    ]

    def slo_ok(r):
        if r.first_token_at is None:
            return False
        ttft = r.first_token_at - r.submitted_at
        n = len(r.output_tokens)
        tpot = (r.last_token_at - r.first_token_at) / (n - 1) if n > 1 else 0.0
        return ttft <= FLEET_TTFT_SLO_S and tpot <= FLEET_TPOT_SLO_S

    good = [r for r in done if slo_ok(r)]
    tokens_all = sum(len(r.output_tokens) for r in done)
    tokens_good = sum(len(r.output_tokens) for r in good)
    outputs = {r.request_id: list(r.output_tokens) for r in done}
    landed = {
        name: sum(1 for r in rep.all_retired() if r.request_id.startswith("fl-"))
        for name, rep in fleet.replicas.items()
    }
    row = {
        "policy": policy,
        "requests": len(requests),
        "requests_finished": len(done),
        "requests_meeting_slo": len(good),
        "tokens": tokens_all,
        "elapsed_s": round(elapsed, 4),
        "tokens_per_second": round(tokens_all / elapsed, 2) if elapsed else 0.0,
        "goodput_tokens_at_slo": tokens_good,
        "goodput_tokens_per_second_at_slo": (
            round(tokens_good / elapsed, 2) if elapsed else 0.0
        ),
        "fleet_sheds": sheds,
        "router_retries": fleet.router.retries,
        "router_retry_counter": metrics.counters.get("serving.router_retry", 0),
        "landed_per_replica": landed,
    }
    return row, outputs


def run_fleet_prefix(params, cfg, policy):
    """The 48x fan-out workload (ISSUE 6) through a PAGED fleet: under
    affinity the whole fan-out co-locates on one replica (fleet-wide
    prefix hits = fanout - 1, the shared prompt prefilled ONCE); blind
    rotation scatters it and EVERY replica pays the shared prefill."""
    from tpu_nexus.serving import ServingFleet

    budget_rows = NUM_SLOTS * PREFIX_MAX_LEN
    num_blocks = 1 + budget_rows // PAGE_SIZE
    lanes = int(os.environ.get("NEXUS_PREFIX_LANES", str(4 * NUM_SLOTS)))
    fleet = ServingFleet(policy=policy)
    warm = np.arange(1, SHARED_LEN + TAIL_LEN + 1, dtype=np.int32)
    for i in range(FLEET_REPLICAS):
        executor = PagedModelExecutor(
            params, cfg, num_slots=lanes, max_len=PREFIX_MAX_LEN,
            page_size=PAGE_SIZE, num_blocks=num_blocks, seed=SEED,
        )
        engine = ServingEngine(executor)
        # warmup compiles per replica: full-prefill bucket, then the
        # extend bucket a prefix hit lands in (warmup tokens < 256 so no
        # warmup prefix can alias a measured lookup)
        engine.submit(warm, 2, request_id="warm-full")
        engine.run_until_drained()
        engine.submit(
            np.concatenate([warm[:-1], [255]]).astype(np.int32), 2,
            request_id="warm-ext",
        )
        engine.run_until_drained()
        engine.metrics = ServingMetrics()
        fleet.add_replica(f"page-{i}", engine)

    requests = make_prefix_requests(np.random.default_rng(SEED))
    t0 = time.perf_counter()
    for i, prompt in enumerate(requests):
        fleet.submit(prompt, PREFIX_GEN, request_id=f"fan-{i}")
    fleet.run_until_drained()
    elapsed = time.perf_counter() - t0

    hits = 0
    shared_tokens = 0
    landed = {}
    for name, rep in fleet.replicas.items():
        s = rep.engine.metrics.summary()
        hits += s["prefix_hits"]
        shared_tokens += s["prefix_shared_tokens"]
        n = sum(1 for r in rep.engine.retired if r.request_id.startswith("fan-"))
        if n:
            landed[name] = n
    outputs = {
        r.request_id: list(r.output_tokens)
        for r in fleet.all_retired()
        if r.request_id.startswith("fan-") and r.state == RequestState.FINISHED
    }
    row = {
        "policy": policy,
        "fanout": FANOUT,
        "shared_len": SHARED_LEN,
        "elapsed_s": round(elapsed, 4),
        "prefix_hits_fleetwide": hits,
        "prefix_shared_tokens_fleetwide": shared_tokens,
        "replicas_touched": len(landed),
        "landed_per_replica": landed,
    }
    return row, outputs


def main_fleet():
    """``--fleet``: ISSUE 19's router, priced.  The SAME skewed Poisson
    arrival schedule (doubled rate over the middle third) through the
    SAME capacity-skewed fleet (one replica at a quarter of the slots)
    under round-robin and under pressure routing; the headline is
    goodput-at-SLO — completed tokens from requests that met the
    TTFT/TPOT targets per wall second — where blind rotation keeps
    feeding the weak replica its full share and pays the queueing in
    violated TTFTs.  Outputs are asserted token-identical across
    policies: routing moves WHERE a request decodes, never WHAT it
    decodes.  The shared-prefix section reruns the ISSUE 6 fan-out
    against a paged fleet: affinity must co-locate the fan-out (fleet
    prefix hits = fanout - 1) while rotation re-prefills the shared
    prompt on every replica it touches."""
    from tpu_nexus.serving import ROUTER_PRESSURE, ROUTER_ROUND_ROBIN

    rng = np.random.default_rng(SEED)
    requests = make_requests(rng, n=FLEET_REQUESTS)
    offsets = _skewed_offsets(rng, FLEET_REQUESTS)
    cfg = bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)

    poisson = {}
    outputs = {}
    for policy in (ROUTER_ROUND_ROBIN, ROUTER_PRESSURE):
        row, outs = run_fleet_poisson(params, cfg, requests, offsets, policy)
        assert len(outs) == FLEET_REQUESTS, (
            f"{policy}: {len(outs)}/{FLEET_REQUESTS} requests finished — "
            "the no-shed fleet dropped work"
        )
        poisson[policy] = row
        outputs[policy] = outs
    assert outputs[ROUTER_ROUND_ROBIN] == outputs[ROUTER_PRESSURE], (
        "router policy changed token streams"
    )

    prefix = {}
    prefix_outputs = {}
    for policy in (ROUTER_ROUND_ROBIN, ROUTER_PRESSURE):
        row, outs = run_fleet_prefix(params, cfg, policy)
        prefix[policy] = row
        prefix_outputs[policy] = outs
    assert prefix_outputs[ROUTER_ROUND_ROBIN] == prefix_outputs[ROUTER_PRESSURE], (
        "router policy changed token streams (prefix fan-out)"
    )

    rr = poisson[ROUTER_ROUND_ROBIN]
    pr = poisson[ROUTER_PRESSURE]
    ratio = (
        pr["goodput_tokens_per_second_at_slo"]
        / rr["goodput_tokens_per_second_at_slo"]
        if rr["goodput_tokens_per_second_at_slo"]
        else 0.0
    )
    result = {
        "metric": "fleet_goodput_at_slo_ratio_pressure_vs_round_robin",
        "value": round(ratio, 4),
        "unit": "x_goodput_tokens_per_second_at_slo",
        "slo": {"ttft_s": FLEET_TTFT_SLO_S, "tpot_s": FLEET_TPOT_SLO_S},
        "fleet": {
            "replicas": FLEET_REPLICAS,
            "strong_slots": NUM_SLOTS,
            "weak_slots": FLEET_WEAK_SLOTS,
            "weak_queue_bound": 6 * FLEET_WEAK_SLOTS,
            "arrival_rps_base": ARRIVAL_RPS,
            "arrival_skew": "rate x2 over the middle third",
            "requests": FLEET_REQUESTS,
        },
        "poisson": poisson,
        "prefix_fanout": prefix,
        "prefix_hit_target": FANOUT - 1,
        "token_identical": True,  # asserted above, both sections
        "seed": SEED,
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_FLEET_OUT", "BENCH_FLEET_r14.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


# -- disaggregated prefill/decode workload (ISSUE 20) ---------------------------

DISAGG_REQUESTS = int(os.environ.get("NEXUS_DISAGG_BENCH_REQUESTS", "64"))
#: arrivals are scheduled in TICK-space (requests per engine tick), not
#: wall-clock: the contended regime this bench prices — a burst landing on
#: slots pinned by live decodes — depends on arrivals per unit of SERVICE,
#: and a wall-clock schedule hits a different regime on every CI box.
#: Latencies are still reported in wall seconds.
DISAGG_ARRIVAL_PER_TICK = float(
    os.environ.get("NEXUS_DISAGG_BENCH_ARRIVAL_PER_TICK", "0.12")
)
DISAGG_SLOTS = int(os.environ.get("NEXUS_DISAGG_BENCH_SLOTS", "8"))
#: same TOTAL slot budget both ways (2 x DISAGG_SLOTS), split by ROLE on
#: the disaggregated side: the prefill tenancy is transient (released at
#: extract), so the prefill replica needs a fraction of the slots and the
#: decode replica — which holds the live batch — takes the rest
DISAGG_PREFILL_SLOTS = max(2, DISAGG_SLOTS // 4)
DISAGG_DECODE_SLOTS = 2 * DISAGG_SLOTS - DISAGG_PREFILL_SLOTS
DISAGG_PAGE = 4
#: exactly two prompt buckets so both fleets warm the same prefill jits:
#: LONG prompts with short decodes (the prefill-heavy half that stalls a
#: fused replica's whole decode batch) and SHORT prompts with long
#: decodes (the latency-sensitive half whose TTFT pays for it)
DISAGG_LONG_PROMPT, DISAGG_LONG_GEN = 48, 4
DISAGG_SHORT_PROMPT, DISAGG_SHORT_GEN = 8, 48
DISAGG_MAX_LEN = DISAGG_LONG_PROMPT + DISAGG_SHORT_GEN


def disagg_bench_model() -> LlamaConfig:
    """:func:`bench_model` in f32: the two modes run DIFFERENT batch
    shapes (role-split slot budgets), and XLA fuses bf16 differently per
    batch size — resolving exact argmax ties differently (the --mesh
    caveat).  f32 keeps the cross-mode identity assert exact."""
    return LlamaConfig(
        vocab_size=512, hidden=256, n_layers=4, n_heads=8, n_kv_heads=4,
        head_dim=32, intermediate=512, max_seq_len=2 * DISAGG_MAX_LEN,
        remat=False, dtype=jnp.float32, param_dtype=jnp.float32,
    )


def _disagg_offsets(rng, n):
    """Cumulative tick-space arrival offsets with the middle third
    COMPRESSED into one burst (the --fleet skew taken to its limit): the
    burst lands while earlier short requests still pin decode slots,
    which is exactly the moment fused admission queues behind decode
    occupancy and a transient prefill tenancy does not.  The peak is
    sized to overflow the decode pool by a FEW requests on purpose: the
    recorded fused-degradation path is part of the price, and those
    requests' queued TTFTs land in the disaggregated percentiles."""
    offsets = np.cumsum(rng.exponential(1.0 / DISAGG_ARRIVAL_PER_TICK, size=n))
    offsets[2 * n // 5 : 3 * n // 5] = offsets[2 * n // 5]
    return offsets


def make_disagg_requests(rng):
    """Mixed traffic, 1/3 long-prefill: the mix where fused interleaving
    hurts — each long prefill rides a tick every decoding request's next
    token is waiting on.  Every prompt gets a UNIQUE first token (ids the
    random body never uses), so no request prefix-hits another: a chance
    1-token shared prefix would route through the COW-extend prefill jit
    and the one-off compile would swamp the p99 this bench exists to
    measure (prefix reuse is --shared-prefix's workload, not this one)."""
    reqs = []
    for i in range(DISAGG_REQUESTS):
        if rng.random() < 1.0 / 3.0:
            plen, gen = DISAGG_LONG_PROMPT, DISAGG_LONG_GEN
        else:
            plen, gen = DISAGG_SHORT_PROMPT, DISAGG_SHORT_GEN
        body = rng.integers(1, 256, size=plen - 1).astype(np.int32)
        head = np.array([260 + i], dtype=np.int32)
        reqs.append({"prompt": np.concatenate([head, body]), "gen": gen})
    return reqs


def _disagg_replica(params, cfg, slots=None):
    """One warmed-up paged engine (both prompt buckets prefilled once, so
    neither side pays first-compile inside the measured pass)."""
    executor = PagedModelExecutor(
        params, cfg, num_slots=DISAGG_SLOTS if slots is None else slots,
        max_len=DISAGG_MAX_LEN, page_size=DISAGG_PAGE, seed=SEED,
    )
    engine = ServingEngine(executor)
    # DISJOINT warmup prompts: arange prompts would share a prefix, so the
    # long one would warm only the tail_start>0 prefill bucket and the
    # first fresh long prompt in the measured pass would pay the compile
    for i, width in enumerate((DISAGG_SHORT_PROMPT, DISAGG_LONG_PROMPT)):
        start = 1 + 100 * i
        engine.submit(np.arange(start, start + width, dtype=np.int32), 2)
    engine.run_until_drained()
    engine.metrics = ServingMetrics()
    return engine


def run_disagg_poisson(params, cfg, requests, offsets, disagg):
    """One open-loop pass of the mixed schedule through a fresh
    two-replica fleet — role-split when ``disagg``, both fused otherwise.
    Returns (summary row, per-request outputs) for the identity assert."""
    from tpu_nexus.serving import DisaggConfig, ServingFleet, percentile
    from tpu_nexus.serving.handoff import ROLE_DECODE, ROLE_PREFILL

    fleet = ServingFleet(disagg=DisaggConfig(), handoff_sleep=lambda s: None)
    roles = (
        (("prefill-0", ROLE_PREFILL, DISAGG_PREFILL_SLOTS),
         ("decode-0", ROLE_DECODE, DISAGG_DECODE_SLOTS))
        if disagg
        else (("fused-0", "fused", DISAGG_SLOTS), ("fused-1", "fused", DISAGG_SLOTS))
    )
    for name, role, slots in roles:
        fleet.add_replica(
            name, _disagg_replica(params, cfg, slots=slots), step=1, role=role
        )
    # warm the handoff path itself (extract/install dispatches) off-clock,
    # same disjoint-prompt discipline as the per-replica warmup
    for i, width in enumerate((DISAGG_SHORT_PROMPT, DISAGG_LONG_PROMPT)):
        start = 1 + 100 * i
        fleet.submit(np.arange(start, start + width, dtype=np.int32), 2)
    fleet.run_until_drained()
    warm_handoffs = fleet.handoffs_completed

    t0 = time.perf_counter()
    idx = 0
    tick_no = 0.0
    while idx < len(requests) or fleet.has_work:
        while idx < len(requests) and offsets[idx] <= tick_no:
            r = requests[idx]
            fleet.submit(r["prompt"], r["gen"], request_id=f"dg-{idx}")
            idx += 1
        if fleet.has_work:
            fleet.tick()
        tick_no += 1.0
    elapsed = time.perf_counter() - t0

    done = [
        r
        for r in fleet.all_retired()
        if r.request_id.startswith("dg-") and r.state == RequestState.FINISHED
    ]
    ttfts = [r.first_token_at - r.submitted_at for r in done]
    tpots = [
        (r.last_token_at - r.first_token_at) / (len(r.output_tokens) - 1)
        for r in done
        if len(r.output_tokens) > 1
    ]
    outputs = {r.request_id: list(r.output_tokens) for r in done}
    row = {
        "mode": "disaggregated" if disagg else "fused",
        "replicas": {name: f"{role}:{slots}" for name, role, slots in roles},
        "requests": len(requests),
        "requests_finished": len(done),
        "elapsed_s": round(elapsed, 4),
        "ttft_p50_s": round(percentile(ttfts, 50.0), 5),
        "ttft_p99_s": round(percentile(ttfts, 99.0), 5),
        "tpot_p50_s": round(percentile(tpots, 50.0), 5),
        "tpot_p99_s": round(percentile(tpots, 99.0), 5),
        "handoffs_completed": fleet.handoffs_completed - warm_handoffs,
        "disagg_fallbacks": fleet.disagg_fallbacks,
        "handoff_log_entries": len(fleet.handoff_log),
    }
    return row, outputs


def main_disagg():
    """``--disagg``: ISSUE 20's split, priced.  The SAME mixed
    long-prefill/short-decode Poisson schedule through the SAME
    two-replica hardware budget, fused vs role-split; the headline is the
    TTFT p99 ratio.  The structural win being measured: a fused replica
    admits new work into ticks shared with the whole decode batch (and
    every long prefill in that tick), while the prefill replica's
    transient tenancy turns admission into prefill-only latency — the
    decode pool's batch never gates a first token.  Outputs are asserted
    token-identical: the handoff moves sealed KV blocks, never the
    argmax."""
    rng = np.random.default_rng(SEED)
    requests = make_disagg_requests(rng)
    offsets = _disagg_offsets(rng, len(requests))
    cfg = disagg_bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)

    rows = {}
    outputs = {}
    for disagg in (False, True):
        row, outs = run_disagg_poisson(params, cfg, requests, offsets, disagg)
        assert len(outs) == DISAGG_REQUESTS, (
            f"{row['mode']}: {len(outs)}/{DISAGG_REQUESTS} requests finished "
            "— the fleet dropped work"
        )
        rows[row["mode"]] = row
        outputs[row["mode"]] = outs
    assert outputs["fused"] == outputs["disaggregated"], (
        "disaggregation changed token streams"
    )
    dg = rows["disaggregated"]
    assert dg["handoffs_completed"] + dg["disagg_fallbacks"] == DISAGG_REQUESTS, (
        "disaggregated accounting leak: every request must either complete "
        "the handoff or be RECORDED degrading to fused"
    )

    fused_p99 = rows["fused"]["ttft_p99_s"]
    disagg_p99 = rows["disaggregated"]["ttft_p99_s"]
    result = {
        "metric": "disagg_ttft_p99_speedup_vs_fused",
        "value": round(fused_p99 / disagg_p99, 4) if disagg_p99 else 0.0,
        "unit": "x_ttft_p99",
        "traffic": {
            "requests": DISAGG_REQUESTS,
            "arrival_per_tick": DISAGG_ARRIVAL_PER_TICK,
            "arrival_skew": "middle fifth arrives as one burst",
            "long_prefill": {
                "prompt": DISAGG_LONG_PROMPT, "gen": DISAGG_LONG_GEN,
                "share": "1/3",
            },
            "short_decode": {
                "prompt": DISAGG_SHORT_PROMPT, "gen": DISAGG_SHORT_GEN,
                "share": "2/3",
            },
        },
        "slots": {
            "fused": [DISAGG_SLOTS, DISAGG_SLOTS],
            "disaggregated": {
                "prefill": DISAGG_PREFILL_SLOTS,
                "decode": DISAGG_DECODE_SLOTS,
            },
        },
        "page_size": DISAGG_PAGE,
        "modes": rows,
        "token_identical": True,  # asserted above
        "seed": SEED,
        "model": "llama-bench-4L-h256-f32",
        "backend": jax.default_backend(),
    }
    out = os.environ.get("NEXUS_DISAGG_OUT", "BENCH_DISAGG_r15.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


def main():
    rng = np.random.default_rng(SEED)
    cfg = bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)
    requests = make_requests(rng)

    engine_tokens, engine_s, engine_steps, _ = run_engine_offline(params, cfg, requests)
    lock_tokens, lock_s = run_lockstep(params, cfg, requests)
    poisson = run_engine_poisson(params, cfg, requests, rng)

    engine_tps = engine_tokens / engine_s if engine_s > 0 else 0.0
    lock_tps = lock_tokens / lock_s if lock_s > 0 else 0.0
    result = {
        "metric": "serving_completed_tokens_per_second",
        "value": round(engine_tps, 2),
        "unit": "tokens/s",
        "lockstep_tokens_per_second": round(lock_tps, 2),
        "speedup_vs_lockstep": round(engine_tps / lock_tps, 3) if lock_tps else None,
        "requests": N_REQUESTS,
        "slots": NUM_SLOTS,
        "prompt_len_range": list(PROMPT_RANGE),
        "gen_tokens_choices": list(GEN_CHOICES),
        "useful_tokens": engine_tokens,
        "engine_elapsed_s": round(engine_s, 4),
        "engine_steps": engine_steps,
        "lockstep_elapsed_s": round(lock_s, 4),
        "poisson": {
            "arrival_rps": ARRIVAL_RPS,
            "ttft_p50_s": round(poisson["ttft_p50_s"], 5),
            "ttft_p99_s": round(poisson["ttft_p99_s"], 5),
            "tpot_p50_s": round(poisson["tpot_p50_s"], 5),
            "tpot_p99_s": round(poisson["tpot_p99_s"], 5),
        },
        "seed": SEED,
        "model": "llama-bench-4L-h256",
        "backend": jax.default_backend(),
    }
    with open(os.environ.get("NEXUS_SERVING_OUT", "BENCH_SERVING_r06.json"), "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    if "--shared-prefix" in sys.argv[1:]:
        main_shared_prefix()
    elif "--spec-k" in sys.argv[1:]:
        main_speculative()
    elif "--mesh" in sys.argv[1:]:
        args = sys.argv[1:]
        after = args[args.index("--mesh") + 1 :]
        main_mesh(after[0] if after and "=" in after[0] else "tp=4")
    elif "--overlap" in sys.argv[1:] or "--decode-steps" in sys.argv[1:]:
        main_overlap()
    elif "--trace" in sys.argv[1:]:
        main_trace()
    elif "--slo" in sys.argv[1:]:
        main_slo()
    elif "--fleet" in sys.argv[1:]:
        main_fleet()
    elif "--disagg" in sys.argv[1:]:
        main_disagg()
    else:
        main()
