"""Serving bench: continuous batching vs the lockstep round loop.

The point of ``tpu_nexus/serving`` in one number: under MIXED generation
lengths, the lockstep loop (``run_serving``-style rounds — every request
in a round waits for the round's longest generation) burns decode steps
on finished rows, while the engine retires and refills slots every
iteration.  Both schedulers process the SAME request set at the SAME slot
count on the SAME jitted model functions; the JSON artifact records both
completed-tokens/s numbers plus the engine's TTFT/TPOT p50/p99 under
Poisson arrivals.

Usage: ``python bench_serving.py`` — prints one JSON line and writes the
artifact itself (``NEXUS_SERVING_OUT``, default BENCH_SERVING_r06.json;
do NOT shell-redirect stdout onto the same file).  Pure CPU, tiny config,
fixed seeds, finishes in seconds (CI hygiene like bench_latency.py).
Knobs: ``NEXUS_SERVING_REQUESTS`` / ``NEXUS_SERVING_SLOTS`` /
``NEXUS_SERVING_ARRIVAL_RPS``.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_nexus.models import LlamaConfig
from tpu_nexus.models.generate import generate
from tpu_nexus.models.llama import llama_init
from tpu_nexus.serving import ModelExecutor, RequestState, ServingEngine, ServingMetrics

SEED = 0
N_REQUESTS = int(os.environ.get("NEXUS_SERVING_REQUESTS", "48"))
NUM_SLOTS = int(os.environ.get("NEXUS_SERVING_SLOTS", "8"))
#: default arrival rate sits UNDER the CPU engine's measured capacity
#: (~30 req/s at this config) so the TTFT/TPOT percentiles reflect
#: scheduling latency, not unbounded queue buildup from overload
ARRIVAL_RPS = float(os.environ.get("NEXUS_SERVING_ARRIVAL_RPS", "24"))
PROMPT_RANGE = (4, 16)
#: mixed-length traffic: the variance is what lockstep rounds pay for —
#: nearly every lockstep round contains one 64-token generation and runs
#: its short requests' slots idle to the end of it
GEN_CHOICES = (2, 8, 64)
MAX_LEN = PROMPT_RANGE[1] + max(GEN_CHOICES)


def bench_model() -> LlamaConfig:
    """Small enough to finish in seconds on CPU, big enough (~6 ms/decode
    step at batch 8) that a decode step costs real compute relative to the
    engine's per-iteration host work — at `LlamaConfig.tiny` scale the
    bench would measure Python dispatch, not scheduling."""
    return LlamaConfig(
        vocab_size=512, hidden=256, n_layers=4, n_heads=8, n_kv_heads=4,
        head_dim=32, intermediate=512, max_seq_len=2 * MAX_LEN, remat=False,
    )


def make_requests(rng):
    reqs = []
    for _ in range(N_REQUESTS):
        n = int(rng.integers(PROMPT_RANGE[0], PROMPT_RANGE[1] + 1))
        reqs.append(
            {
                "prompt": rng.integers(1, 256, size=n).astype(np.int32),
                "gen": int(rng.choice(GEN_CHOICES)),
            }
        )
    return reqs


def run_engine_offline(params, cfg, requests):
    """All requests queued at t=0: pure completed-tokens/s."""
    executor = ModelExecutor(params, cfg, num_slots=NUM_SLOTS, max_len=MAX_LEN, seed=SEED)
    engine = ServingEngine(executor)
    # warmup: one request per prefill bucket in play + the decode step
    for width in (PROMPT_RANGE[0], PROMPT_RANGE[1]):
        engine.submit(np.arange(1, width + 1, dtype=np.int32), 2)
    engine.run_until_drained()
    engine.metrics = ServingMetrics()
    n_warm = len(engine.retired)

    t0 = time.perf_counter()
    for i, r in enumerate(requests):
        engine.submit(r["prompt"], r["gen"], request_id=f"off-{i}")
    engine.run_until_drained()
    elapsed = time.perf_counter() - t0
    done = engine.retired[n_warm:]
    tokens = sum(
        len(r.output_tokens) for r in done if r.state == RequestState.FINISHED
    )
    return tokens, elapsed, engine.steps


def run_engine_poisson(params, cfg, requests, rng):
    """Open-loop Poisson arrivals: the latency SLO view (TTFT/TPOT)."""
    executor = ModelExecutor(params, cfg, num_slots=NUM_SLOTS, max_len=MAX_LEN, seed=SEED)
    engine = ServingEngine(executor)
    for width in (PROMPT_RANGE[0], PROMPT_RANGE[1]):
        engine.submit(np.arange(1, width + 1, dtype=np.int32), 2)
    engine.run_until_drained()
    engine.metrics = metrics = ServingMetrics()

    offsets = np.cumsum(rng.exponential(1.0 / ARRIVAL_RPS, size=len(requests)))
    t0 = time.perf_counter()
    idx = 0
    while idx < len(requests) or engine.has_work:
        now = time.perf_counter() - t0
        while idx < len(requests) and offsets[idx] <= now:
            engine.submit(requests[idx]["prompt"], requests[idx]["gen"], request_id=f"poi-{idx}")
            idx += 1
        if engine.has_work:
            engine.step()
        elif idx < len(requests):
            time.sleep(min(0.001, offsets[idx] - now))
    return metrics.summary()


def run_lockstep(params, cfg, requests):
    """The run_serving discipline: rounds of NUM_SLOTS requests, each
    round decoding to its LONGEST request's budget (prompts right-padded
    with per-row prompt_lengths — the ragged ``generate`` contract).
    Useful tokens = what each request actually asked for; the overshoot
    is the waste this bench prices."""
    width = PROMPT_RANGE[1]
    gen_fns = {}
    for t in sorted({g for g in GEN_CHOICES}):
        gen_fns[t] = jax.jit(
            functools.partial(
                generate, cfg=cfg, max_new_tokens=t, max_len=width + t
            )
        )
    rounds = [requests[i : i + NUM_SLOTS] for i in range(0, len(requests), NUM_SLOTS)]

    def batch_of(round_reqs):
        padded = np.zeros((NUM_SLOTS, width), np.int32)
        lens = np.ones(NUM_SLOTS, np.int32)  # pad rows decode garbage, uncounted
        for j, r in enumerate(round_reqs):
            padded[j, : len(r["prompt"])] = r["prompt"]
            lens[j] = len(r["prompt"])
        return jnp.asarray(padded), jnp.asarray(lens)

    # warmup every distinct round shape (compile excluded, like run_serving)
    for t in gen_fns:
        p, l = batch_of(rounds[0])
        jax.block_until_ready(gen_fns[t](params, p, prompt_lengths=l))

    t0 = time.perf_counter()
    useful = 0
    for round_reqs in rounds:
        t = max(r["gen"] for r in round_reqs)
        p, l = batch_of(round_reqs)
        jax.block_until_ready(gen_fns[t](params, p, prompt_lengths=l))
        useful += sum(r["gen"] for r in round_reqs)
    return useful, time.perf_counter() - t0


def main():
    rng = np.random.default_rng(SEED)
    cfg = bench_model()
    params = llama_init(jax.random.PRNGKey(SEED), cfg)
    requests = make_requests(rng)

    engine_tokens, engine_s, engine_steps = run_engine_offline(params, cfg, requests)
    lock_tokens, lock_s = run_lockstep(params, cfg, requests)
    poisson = run_engine_poisson(params, cfg, requests, rng)

    engine_tps = engine_tokens / engine_s if engine_s > 0 else 0.0
    lock_tps = lock_tokens / lock_s if lock_s > 0 else 0.0
    result = {
        "metric": "serving_completed_tokens_per_second",
        "value": round(engine_tps, 2),
        "unit": "tokens/s",
        "lockstep_tokens_per_second": round(lock_tps, 2),
        "speedup_vs_lockstep": round(engine_tps / lock_tps, 3) if lock_tps else None,
        "requests": N_REQUESTS,
        "slots": NUM_SLOTS,
        "prompt_len_range": list(PROMPT_RANGE),
        "gen_tokens_choices": list(GEN_CHOICES),
        "useful_tokens": engine_tokens,
        "engine_elapsed_s": round(engine_s, 4),
        "engine_steps": engine_steps,
        "lockstep_elapsed_s": round(lock_s, 4),
        "poisson": {
            "arrival_rps": ARRIVAL_RPS,
            "ttft_p50_s": round(poisson["ttft_p50_s"], 5),
            "ttft_p99_s": round(poisson["ttft_p99_s"], 5),
            "tpot_p50_s": round(poisson["tpot_p50_s"], 5),
            "tpot_p99_s": round(poisson["tpot_p99_s"], 5),
        },
        "seed": SEED,
        "model": "llama-bench-4L-h256",
        "backend": jax.default_backend(),
    }
    with open(os.environ.get("NEXUS_SERVING_OUT", "BENCH_SERVING_r06.json"), "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
