"""Control-plane rules: the invariants Go's compiler enforced for the
reference supervisor, re-stated over this repo's Python control plane.

NX001  decision-taxonomy totality (supervisor/taxonomy.py)
NX002  CQL schema <-> model <-> statement parity (checkpoint/*)
NX003  broad except without a ``# noqa: BLE001 - <reason>`` justification
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set

from tools.nxlint.engine import (
    Finding,
    Module,
    Project,
    Rule,
    RuleVisitor,
    register,
)

TAXONOMY_PATH = "supervisor/taxonomy.py"
MODELS_PATH = "checkpoint/models.py"
CQL_PATH = "checkpoint/cql.py"
STORE_PATH = "checkpoint/store.py"
SCHEMA_FILE = "schema.cql"


def _attr_names(node: ast.AST, owner: str) -> Set[str]:
    """``{owner}.X`` attribute references directly inside a container node."""
    names = set()
    for child in ast.walk(node):
        if (
            isinstance(child, ast.Attribute)
            and isinstance(child.value, ast.Name)
            and child.value.id == owner
        ):
            names.add(child.attr)
    return names


def _module_assign(tree: ast.Module, name: str) -> Optional[ast.AST]:
    """Module-level ``name = value`` / ``name: T = value`` -> the value node."""
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                if isinstance(target, ast.Name) and target.id == name:
                    return stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            if isinstance(stmt.target, ast.Name) and stmt.target.id == name:
                return stmt.value
    return None


@register
class TaxonomyTotalityRule(Rule):
    """NX001: every ``DecisionAction`` constant must have a ``DECISION_STAGE``
    row, an ``ACTION_MESSAGES`` human message, and belong to exactly one of
    ``DELETES_JOB`` / ``NON_DELETING_ACTIONS``.  An unmapped action is the
    bug class where event classification raises KeyError mid-incident."""

    rule_id = "NX001"
    description = "decision taxonomy must be total over DecisionAction constants"

    def check_project(self, project: Project) -> Iterator[Finding]:
        module = project.find_module(TAXONOMY_PATH)
        if module is None or module.tree is None:
            return
        class_node = next(
            (
                n
                for n in module.tree.body
                if isinstance(n, ast.ClassDef) and n.name == "DecisionAction"
            ),
            None,
        )
        if class_node is None:
            yield self.finding(
                module, module.tree, "DecisionAction class not found in taxonomy module"
            )
            return
        constants: Dict[str, ast.AST] = {}
        for stmt in class_node.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant):
                targets = stmt.targets
            elif (
                isinstance(stmt, ast.AnnAssign)  # TO_NEW: str = "ToNew"
                and isinstance(stmt.value, ast.Constant)
            ):
                targets = [stmt.target]
            else:
                continue
            for target in targets:
                if isinstance(target, ast.Name) and target.id.isupper():
                    constants[target.id] = stmt

        tables = {}
        for table in ("DECISION_STAGE", "ACTION_MESSAGES", "DELETES_JOB", "NON_DELETING_ACTIONS"):
            value = _module_assign(module.tree, table)
            if value is None:
                yield self.finding(
                    module, module.tree, f"required taxonomy table {table} not found"
                )
                continue
            if table in ("DECISION_STAGE", "ACTION_MESSAGES"):
                members: Set[str] = set()
                if isinstance(value, ast.Dict):
                    for key in value.keys:
                        if key is not None:
                            members |= _attr_names(key, "DecisionAction")
            else:
                members = _attr_names(value, "DecisionAction")
            tables[table] = (value, members)

        # optional-but-total tables: auxiliary consequence maps (the
        # serving-fleet recovery table, ISSUE 9).  Absence is fine — not
        # every taxonomy grows every consumer — but a PRESENT table must be
        # total over DecisionAction like the required ones: the fleet
        # controller indexes it directly, so a hole is the same midnight
        # KeyError class NX001 exists to stop.
        for table in ("SERVING_POD_RECOVERY",):
            value = _module_assign(module.tree, table)
            if value is None:
                continue
            members = set()
            if isinstance(value, ast.Dict):
                for key in value.keys:
                    if key is not None:
                        members |= _attr_names(key, "DecisionAction")
            tables[table] = (value, members)
            for name, node in sorted(constants.items()):
                if name not in members:
                    yield self.finding(
                        module,
                        node,
                        f"DecisionAction.{name} has no {table} row "
                        "(serving-fleet recovery undeclared)",
                    )

        for name, node in sorted(constants.items()):
            if "DECISION_STAGE" in tables and name not in tables["DECISION_STAGE"][1]:
                yield self.finding(
                    module, node, f"DecisionAction.{name} has no DECISION_STAGE row"
                )
            if "ACTION_MESSAGES" in tables and name not in tables["ACTION_MESSAGES"][1]:
                yield self.finding(
                    module,
                    node,
                    f"DecisionAction.{name} has no human message in ACTION_MESSAGES",
                )
            if "DELETES_JOB" in tables and "NON_DELETING_ACTIONS" in tables:
                deleting = name in tables["DELETES_JOB"][1]
                non_deleting = name in tables["NON_DELETING_ACTIONS"][1]
                if not deleting and not non_deleting:
                    yield self.finding(
                        module,
                        node,
                        f"DecisionAction.{name} is in neither DELETES_JOB nor "
                        "NON_DELETING_ACTIONS (delete behavior undeclared)",
                    )
                elif deleting and non_deleting:
                    yield self.finding(
                        module,
                        node,
                        f"DecisionAction.{name} is in both DELETES_JOB and "
                        "NON_DELETING_ACTIONS",
                    )

        # stale rows: table members that no longer name a constant
        for table, payload in tables.items():
            value, members = payload
            for member in sorted(members - set(constants)):
                yield self.finding(
                    module,
                    value,
                    f"{table} references unknown DecisionAction.{member}",
                )


_CQL_COLUMN_RE = re.compile(r"^\s*([a-z_][a-z0-9_]*)\s+[a-z<]")


def parse_schema_columns(schema_cql: str) -> List[str]:
    """Column names of the ``create table`` block: lines between the opening
    paren and PRIMARY KEY, comments stripped."""
    columns: List[str] = []
    in_table = False
    for raw in schema_cql.splitlines():
        line = raw.split("--", 1)[0].rstrip()
        lowered = line.strip().lower()
        if not in_table:
            if lowered.startswith("create table"):
                in_table = True
            continue
        if lowered.startswith("primary key") or lowered.startswith(")"):
            in_table = False
            continue
        m = _CQL_COLUMN_RE.match(line)
        if m:
            columns.append(m.group(1))
    return columns


@register
class SchemaDriftRule(Rule):
    """NX002: schema.cql columns == CheckpointedRequest fields ==
    store._COLUMNS == the upsert statement's column dict.  CQL upserts write
    the full row, so one stray field name means every write fails (or worse:
    silently drops a column) at runtime against a real cluster."""

    rule_id = "NX002"
    description = "CQL schema, dataclass model and statements must agree column-for-column"

    def check_project(self, project: Project) -> Iterator[Finding]:
        models = project.find_module(MODELS_PATH)
        if models is None or models.tree is None:
            return
        schema_text = project.read_sibling(models, SCHEMA_FILE)
        if schema_text is None:
            yield self.finding(
                models, models.tree, f"{SCHEMA_FILE} not found next to {models.rel_path}"
            )
            return
        schema_cols = set(parse_schema_columns(schema_text))
        if not schema_cols:
            yield self.finding(
                models, models.tree, f"no columns parsed from {SCHEMA_FILE}"
            )
            return

        class_node = next(
            (
                n
                for n in models.tree.body
                if isinstance(n, ast.ClassDef) and n.name == "CheckpointedRequest"
            ),
            None,
        )
        if class_node is None:
            yield self.finding(models, models.tree, "CheckpointedRequest class not found")
            return
        fields = {
            stmt.target.id
            for stmt in class_node.body
            if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name)
        }
        for name in sorted(schema_cols - fields):
            yield self.finding(
                models,
                class_node,
                f"schema column '{name}' has no CheckpointedRequest field",
            )
        for name in sorted(fields - schema_cols):
            yield self.finding(
                models,
                class_node,
                f"CheckpointedRequest field '{name}' has no schema.cql column",
            )

        store = project.find_module(STORE_PATH)
        if store is not None and store.tree is not None:
            value = _module_assign(store.tree, "_COLUMNS")
            if isinstance(value, (ast.List, ast.Tuple)):
                cols = {
                    e.value
                    for e in value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
                for name in sorted(schema_cols - cols):
                    yield self.finding(
                        store, value, f"schema column '{name}' missing from _COLUMNS"
                    )
                for name in sorted(cols - schema_cols):
                    yield self.finding(
                        store, value, f"_COLUMNS entry '{name}' has no schema.cql column"
                    )

        cql = project.find_module(CQL_PATH)
        if cql is not None and cql.tree is not None:
            upsert_keys = self._upsert_keys(cql.tree)
            if upsert_keys is None:
                # fail CLOSED: a renamed `values` dict must not silently
                # skip the statement-parity comparison
                yield self.finding(
                    cql,
                    cql.tree,
                    "could not locate the `values = {...}` column dict in "
                    "upsert_checkpoint (statement parity unverifiable)",
                )
            else:
                node, keys = upsert_keys
                for name in sorted(schema_cols - keys):
                    yield self.finding(
                        cql,
                        node,
                        f"schema column '{name}' not written by upsert_checkpoint",
                    )
                for name in sorted(keys - schema_cols):
                    yield self.finding(
                        cql,
                        node,
                        f"upsert_checkpoint writes '{name}' which is not a schema.cql column",
                    )

    @staticmethod
    def _upsert_keys(tree: ast.Module):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == "upsert_checkpoint":
                for stmt in ast.walk(node):
                    if (
                        isinstance(stmt, ast.Assign)
                        and any(
                            isinstance(t, ast.Name) and t.id == "values"
                            for t in stmt.targets
                        )
                        and isinstance(stmt.value, ast.Dict)
                    ):
                        keys = {
                            k.value
                            for k in stmt.value.keys
                            if isinstance(k, ast.Constant) and isinstance(k.value, str)
                        }
                        return stmt, keys
        return None


_BLE_JUSTIFICATION_RE = re.compile(r"#\s*noqa:\s*BLE001\s*-\s*\S")


class _BroadExceptVisitor(RuleVisitor):
    _BROAD = ("Exception", "BaseException")

    def _clause_text(self, node: ast.ExceptHandler) -> str:
        """All source lines of the except clause itself (a wrapped tuple of
        exception types spans several lines; the justification may sit on
        any of them)."""
        last = node.lineno
        if node.type is not None:
            last = getattr(node.type, "end_lineno", None) or node.lineno
        return "\n".join(
            self.module.line_text(line) for line in range(node.lineno, last + 1)
        )

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._is_broad(node.type) and not _BLE_JUSTIFICATION_RE.search(
            self._clause_text(node)
        ):
            what = "bare except" if node.type is None else f"except {ast.unparse(node.type)}"
            self.report(
                node,
                f"{what} without a '# noqa: BLE001 - <reason>' justification",
            )
        self.generic_visit(node)

    def _is_broad(self, type_node: Optional[ast.expr]) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Name):
            return type_node.id in self._BROAD
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(e) for e in type_node.elts)
        return False


@register
class BroadExceptRule(Rule):
    """NX003: ``except Exception`` / bare ``except`` swallow the control
    plane's own bugs; each one must carry the repo's documented
    ``# noqa: BLE001 - <reason>`` annotation (convention: core/telemetry.py)
    on the except line."""

    rule_id = "NX003"
    description = "broad except handlers must be justified inline"

    def check_module(self, module: Module) -> Iterator[Finding]:
        if module.tree is None:
            return
        visitor = _BroadExceptVisitor(self, module)
        visitor.visit(module.tree)
        yield from visitor.findings
