"""JAX tracing-safety rules for the workload hot paths.

NX010  host-sync ops inside traced (jit / shard_map / lax control-flow) code
NX011  PRNG key consumed twice without an intervening split/rebind
NX012  mesh-axis string literals that are not axes of parallel/mesh.py

All three are syntactic approximations of dynamic properties; each carries
a per-line ``# nxlint: disable=RULE`` escape hatch for the justified cases.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.nxlint.engine import (
    Finding,
    Module,
    Project,
    Rule,
    RuleVisitor,
    register,
)
from tools.nxlint.flow import CallGraph, flow_for

MESH_PATH = "parallel/mesh.py"

#: callables whose function-valued arguments run under a JAX trace.  Matched
#: by terminal attribute/name, so ``jax.jit``, ``jit`` and ``jax.lax.scan``
#: all resolve.
_TRACING_ENTRY_POINTS = frozenset(
    {
        "jit",
        "pjit",
        "pmap",
        "vmap",
        "grad",
        "value_and_grad",
        "shard_map",
        "shard_map_compat",
        "scan",
        "while_loop",
        "fori_loop",
        "cond",
        "switch",
        "checkpoint",
        "remat",
    }
)

_PARTIAL_NAMES = frozenset({"partial"})


def _terminal_name(func: ast.expr) -> Optional[str]:
    """``jax.lax.scan`` -> ``scan``; ``jit`` -> ``jit``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_tracing_decorator(dec: ast.expr) -> bool:
    name = _terminal_name(dec)
    if name in _TRACING_ENTRY_POINTS:
        return True
    if isinstance(dec, ast.Call):
        inner = _terminal_name(dec.func)
        if inner in _TRACING_ENTRY_POINTS:
            return True
        if inner in _PARTIAL_NAMES and dec.args:
            return _terminal_name(dec.args[0]) in _TRACING_ENTRY_POINTS
    return False


class _FunctionIndex:
    """Lexically-scoped function resolution for one module.

    Names resolve from a reference site outward through the enclosing
    function scopes to module level — two same-named nested helpers in
    different functions (``def step`` inside every jitted builder, the
    dominant JAX pattern) must not be conflated."""

    def __init__(self, tree: ast.Module) -> None:
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        #: alias name -> (target function name, the assign node it was made at)
        self.partial_aliases: Dict[str, Tuple[str, ast.AST]] = {}
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _terminal_name(node.value.func) in _PARTIAL_NAMES
                and node.value.args
                and isinstance(node.value.args[0], ast.Name)
            ):
                self.partial_aliases[node.targets[0].id] = (
                    node.value.args[0].id,
                    node,
                )
        self._local_defs_cache: Dict[int, Dict[str, ast.AST]] = {}

    def all_functions(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def _local_defs(self, scope: ast.AST) -> Dict[str, ast.AST]:
        cached = self._local_defs_cache.get(id(scope))
        if cached is not None:
            return cached
        defs: Dict[str, ast.AST] = {}

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.setdefault(child.name, child)
                    continue  # don't descend into nested scopes
                if isinstance(child, ast.ClassDef):
                    continue
                walk(child)

        walk(scope)
        self._local_defs_cache[id(scope)] = defs
        return defs

    def resolve(self, name: str, site: ast.AST) -> Optional[ast.AST]:
        """The def node ``name`` refers to at ``site``, through at most one
        ``partial`` alias; None for imports/builtins."""
        alias = self.partial_aliases.get(name)
        if alias is not None:
            name, site = alias
        node: Optional[ast.AST] = site
        while node is not None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                found = self._local_defs(node).get(name)
                if found is not None:
                    return found
            node = self.parents.get(node)
        return None


def seed_traced_functions(tree: ast.Module, index: _FunctionIndex) -> Set[ast.AST]:
    """The DIRECTLY traced defs: tracing decorators, or the function
    (possibly through one ``partial`` alias) passed by name to a tracing
    entry point — resolved lexically from the call site."""
    traced: Set[ast.AST] = set()
    for node in index.all_functions():
        if any(_is_tracing_decorator(d) for d in node.decorator_list):
            traced.add(node)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal_name(node.func) not in _TRACING_ENTRY_POINTS:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name):
                fn = index.resolve(arg.id, node)
                if fn is not None:
                    traced.add(fn)
    return traced


def traced_functions(tree: ast.Module) -> Set[ast.AST]:
    """Function defs that run under a JAX trace, closed transitively over
    same-module name calls (the LEXICAL pass — the flow-backed closure in
    :class:`HostSyncInJitRule` also follows ``self.method`` and imported
    helpers through the call graph)."""
    index = _FunctionIndex(tree)
    traced = seed_traced_functions(tree, index)
    # transitive closure: a function called by name from a traced body is
    # itself traced (helpers like a sampler called inside a scanned body)
    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    callee = index.resolve(node.func.id, node)
                    if callee is not None and callee not in traced:
                        traced.add(callee)
                        changed = True
    return traced


#: attribute reads that yield static (trace-time) python values even when
#: their base is a traced array — the taint sanitizers
_STATIC_ATTRS = frozenset({"shape", "ndim", "size", "dtype"})
#: attribute reads that yield another array view of a traced base
_ARRAY_ATTRS = frozenset({"T", "mT", "real", "imag", "at"})
#: annotations naming plain python scalars: such parameters are static
#: arguments at trace time, not traced arrays
_SCALAR_ANNOTATIONS = frozenset({"int", "float", "bool", "str", "bytes"})


def _annotation_is_scalar(annotation: Optional[ast.expr]) -> bool:
    if annotation is None:
        return False
    names = {
        n.id if isinstance(n, ast.Name) else n.attr
        for n in ast.walk(annotation)
        if isinstance(n, (ast.Name, ast.Attribute))
    }
    if {"Array", "ArrayLike", "ndarray", "jax", "jnp"} & names:
        return False
    return bool(_SCALAR_ANNOTATIONS & {str(c.value) for c in ast.walk(annotation) if isinstance(c, ast.Constant)} | (_SCALAR_ANNOTATIONS & names))


class _TaintTracker:
    """Forward taint pass over a traced function: names flowing from
    array-typed parameters are tainted; ``.shape``-style reads and ``len()``
    sanitize.  ``float()``/``int()`` on a tainted expression is a host sync;
    on clean (shape/config arithmetic) values it is trace-time constant
    folding and fine."""

    def __init__(self, fn: ast.AST) -> None:
        self.tainted: Set[str] = set()
        args = fn.args
        for arg in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if not _annotation_is_scalar(arg.annotation):
                self.tainted.add(arg.arg)
        if args.vararg is not None:
            self.tainted.add(args.vararg.arg)
        if args.kwarg is not None:
            self.tainted.add(args.kwarg.arg)

    def expr_tainted(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            # .shape/.ndim/... are static; .T/.at/... stay arrays; any other
            # plain attribute read is a config/scalar access (cfg.n_experts)
            if expr.attr in _STATIC_ATTRS:
                return False
            if expr.attr in _ARRAY_ATTRS:
                return self.expr_tainted(expr.value)
            return False
        if isinstance(expr, ast.Call):
            if _terminal_name(expr.func) == "len":
                return False
            parts: List[ast.expr] = list(expr.args) + [
                kw.value for kw in expr.keywords
            ]
            if isinstance(expr.func, ast.Attribute):
                # method call: x.reshape(...) carries x's taint
                parts.append(expr.func.value)
            return any(self.expr_tainted(p) for p in parts)
        return any(
            self.expr_tainted(c)
            for c in ast.iter_child_nodes(expr)
            if isinstance(c, ast.expr)
        )

    def bind(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            is_tainted = self.expr_tainted(stmt.iter)
            names = _assigned_names_of_target(stmt.target)
        else:
            value = getattr(stmt, "value", None)
            if not isinstance(value, ast.expr):
                return
            is_tainted = self.expr_tainted(value)
            names = _assigned_names(stmt)
            if isinstance(stmt, ast.AugAssign):
                # `acc += 1` keeps acc's existing taint — the target is an
                # operand, not a fresh binding
                is_tainted = is_tainted or any(n in self.tainted for n in names)
        for name in names:
            if is_tainted:
                self.tainted.add(name)
            else:
                self.tainted.discard(name)


def _own_exprs(stmt: ast.stmt):
    """The expressions belonging to this statement itself (not to statements
    nested in its body/orelse/... blocks)."""
    for _, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, ast.expr):
                    yield item
                elif isinstance(item, (ast.withitem, ast.keyword)):
                    yield from (
                        v for _, v in ast.iter_fields(item) if isinstance(v, ast.expr)
                    )


def _child_blocks(stmt: ast.stmt):
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if isinstance(block, list) and block and isinstance(block[0], ast.stmt):
            yield block
    for handler in getattr(stmt, "handlers", []) or []:
        yield handler.body


class _HostSyncVisitor(RuleVisitor):
    def __init__(self, rule: Rule, module: Module, taint: _TaintTracker) -> None:
        super().__init__(rule, module)
        self.taint = taint

    def check_call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "item" and not node.args:
            self.report(node, "host sync under trace: .item() forces device->host transfer")
        elif isinstance(func, ast.Name) and func.id in ("float", "int") and node.args:
            if any(self.taint.expr_tainted(a) for a in node.args):
                self.report(
                    node,
                    f"host sync under trace: {func.id}() cast concretizes a traced value",
                )
        elif isinstance(func, ast.Attribute) and func.attr in ("array", "asarray"):
            base = func.value
            # same taint gate as the casts: np.array([1.0]) over static
            # values is trace-time constant construction, not a host sync
            if (
                isinstance(base, ast.Name)
                and base.id in ("np", "numpy")
                and any(self.taint.expr_tainted(a) for a in node.args)
            ):
                self.report(
                    node,
                    f"host sync under trace: {base.id}.{func.attr}() materializes on host",
                )
        elif _terminal_name(func) == "device_get":
            self.report(node, "host sync under trace: jax.device_get()")
        elif isinstance(func, ast.Name) and func.id == "print":
            self.report(
                node,
                "print under trace runs once at trace time (use jax.debug.print)",
            )


@register
class HostSyncInJitRule(Rule):
    """NX010: ``.item()`` / ``float()``/``int()`` casts / ``np.array`` /
    ``jax.device_get`` / ``print`` inside functions that run under
    ``jax.jit`` / ``shard_map`` / ``lax`` control flow.  On TPU these either
    fail at trace time or silently freeze a trace-time constant.

    With the call graph available (ISSUE 16) the traced closure also
    follows ``self.method()`` calls through the enclosing class and
    from-imported helpers into their defining modules — a sampler moved
    from the jitted body into a sibling module stays covered.  When the
    graph cannot be built the per-module lexical closure still runs
    (NX020 reports the breakage)."""

    rule_id = "NX010"
    description = "no host-synchronizing ops inside traced functions"
    #: flip off to force the lexical fallback (also the behavior when the
    #: call graph fails to build)
    flow_enabled = True

    #: provenance edges the traced closure follows.  "attr"/"var" edges
    #: (instance methods through inferred attribute types) are excluded:
    #: an object handed INTO a jitted function is a static argument, and
    #: following its methods would drag untraced config helpers in.
    _FOLLOW_VIAS = frozenset({"local", "module-def", "import", "self"})

    def check_project(self, project: Project) -> Iterator[Finding]:
        graph: Optional[CallGraph] = None
        if self.flow_enabled:
            try:
                graph = flow_for(project)
            except Exception:  # noqa: BLE001 - graph failure degrades to the lexical pass; NX020 reports it
                graph = None
        if graph is None:
            for module in project.modules:
                yield from self._check_module_lexical(module)
            return
        yield from self._check_project_flow(project, graph)

    def _check_module_lexical(self, module: Module) -> Iterator[Finding]:
        if module.tree is None:
            return
        seen: Set[Tuple[int, int, str]] = set()
        for fn in traced_functions(module.tree):
            yield from self._scan_traced(fn, module, seen)

    def _check_project_flow(
        self, project: Project, graph: CallGraph
    ) -> Iterator[Finding]:
        indexes: Dict[str, _FunctionIndex] = {}
        #: id(def node) -> (def node, module it lives in)
        traced: Dict[int, Tuple[ast.AST, Module]] = {}
        for module in project.modules:
            if module.tree is None:
                continue
            index = _FunctionIndex(module.tree)
            indexes[module.rel_path] = index
            for fn in seed_traced_functions(module.tree, index):
                traced[id(fn)] = (fn, module)
        changed = True
        while changed:
            changed = False
            for fn, module in list(traced.values()):
                index = indexes[module.rel_path]
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    for callee, mod in self._call_targets(node, module, index, graph):
                        if id(callee) not in traced:
                            traced[id(callee)] = (callee, mod)
                            changed = True
        seen_by_module: Dict[str, Set[Tuple[int, int, str]]] = {}
        for fn, module in traced.values():
            seen = seen_by_module.setdefault(module.rel_path, set())
            yield from self._scan_traced(fn, module, seen)

    def _call_targets(
        self,
        node: ast.Call,
        module: Module,
        index: _FunctionIndex,
        graph: CallGraph,
    ) -> List[Tuple[ast.AST, Module]]:
        """Defs this call pulls into the traced closure.  Lexical (partial-
        aware) resolution wins for plain names; the graph adds the
        cross-module and ``self.method`` edges the lexical pass cannot
        see."""
        if isinstance(node.func, ast.Name):
            local = index.resolve(node.func.id, node)
            if local is not None:
                return [(local, module)]
        return [
            (info.node, info.module)
            for info, via in graph.resolve_call(node, module)
            if via in self._FOLLOW_VIAS
        ]

    def _scan_traced(
        self, fn: ast.AST, module: Module, seen: Set[Tuple[int, int, str]]
    ) -> Iterator[Finding]:
        taint = _TaintTracker(fn)
        visitor = _HostSyncVisitor(self, module, taint)
        self._scan(fn.body, visitor, taint)
        for finding in visitor.findings:
            key = (finding.line, finding.col, finding.message)
            if key not in seen:
                seen.add(key)
                yield finding

    def _scan(self, stmts, visitor: _HostSyncVisitor, taint: _TaintTracker) -> None:
        """Statement-ordered scan so taint bindings apply before later uses;
        nested defs are skipped (they get their own pass when traced)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            for expr in _own_exprs(stmt):
                for node in ast.walk(expr):
                    if isinstance(node, ast.Call):
                        visitor.check_call(node)
            taint.bind(stmt)
            for block in _child_blocks(stmt):
                self._scan(block, visitor, taint)


# -- NX011 ---------------------------------------------------------------------

#: jax.random functions that CONSUME their key argument.  ``PRNGKey``/``key``
#: mint keys; ``fold_in`` derives per-step keys from a reusable base —
#: reusing the base with different fold data is the intended pattern.
_NON_CONSUMING = frozenset({"PRNGKey", "key", "fold_in", "wrap_key_data", "key_data"})


def _random_key_arg(node: ast.Call) -> Optional[str]:
    """If ``node`` is a key-consuming ``jax.random.*`` call, the plain-Name
    key argument (first positional or ``key=``), else None."""
    func = node.func
    if not (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Attribute)
        and func.value.attr == "random"
        and isinstance(func.value.value, ast.Name)
        and func.value.value.id == "jax"
    ):
        return None
    if func.attr in _NON_CONSUMING:
        return None
    key_expr: Optional[ast.expr] = node.args[0] if node.args else None
    for kw in node.keywords:
        if kw.arg == "key":
            key_expr = kw.value
    if isinstance(key_expr, ast.Name):
        return key_expr.id
    return None


def _assigned_names(stmt: ast.stmt) -> Set[str]:
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    names: Set[str] = set()
    for target in targets:
        for child in ast.walk(target):
            if isinstance(child, ast.Name):
                names.add(child.id)
    for child in ast.walk(stmt):
        if isinstance(child, ast.NamedExpr) and isinstance(child.target, ast.Name):
            names.add(child.target.id)
    return names


class _KeyFlow:
    """Linear-ish scan of one function scope: track, per key name, whether it
    has already been consumed by a ``jax.random.*`` call.  If/try branches
    fork the state and merge conservatively (consumed only if consumed in
    every branch); loop bodies run twice to catch cross-iteration reuse."""

    def __init__(self, rule: Rule, module: Module) -> None:
        self.rule = rule
        self.module = module
        self.findings: List[Finding] = []
        self._reported: Set[Tuple[int, int, str]] = set()

    def run(self, fn: ast.AST) -> None:
        self._process_block(fn.body, {})

    def _process_block(self, stmts: List[ast.stmt], state: Dict[str, bool]) -> None:
        for stmt in stmts:
            self._process_stmt(stmt, state)

    def _process_stmt(self, stmt: ast.stmt, state: Dict[str, bool]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate scope, analyzed on its own
        if isinstance(stmt, ast.If):
            branches = [stmt.body, stmt.orelse]
            forks = []
            for branch in branches:
                fork = dict(state)
                self._consume_in_expr(stmt.test, fork)
                self._process_block(branch, fork)
                forks.append(fork)
            self._merge(state, forks)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._consume_in_expr(stmt.iter, state)
            for _ in range(2):  # second pass models the loop back-edge
                for name in _assigned_names_of_target(stmt.target):
                    state[name] = False
                self._process_block(stmt.body, state)
            self._process_block(stmt.orelse, state)
            return
        if isinstance(stmt, ast.While):
            for _ in range(2):
                self._consume_in_expr(stmt.test, state)
                self._process_block(stmt.body, state)
            self._process_block(stmt.orelse, state)
            return
        if isinstance(stmt, (ast.Try,)):
            self._process_block(stmt.body, state)
            forks = []
            for handler in stmt.handlers:
                fork = dict(state)
                self._process_block(handler.body, fork)
                forks.append(fork)
            if forks:
                self._merge(state, forks + [dict(state)])
            self._process_block(stmt.orelse, state)
            self._process_block(stmt.finalbody, state)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._consume_in_expr(item.context_expr, state)
            self._process_block(stmt.body, state)
            return
        # simple statement: consumptions in the expression tree first, then
        # (re)bindings take effect
        self._consume_in_stmt(stmt, state)
        for name in _assigned_names(stmt):
            state[name] = False

    def _consume_in_stmt(self, stmt: ast.stmt, state: Dict[str, bool]) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._consume_call(node, state)

    def _consume_in_expr(self, expr: Optional[ast.expr], state: Dict[str, bool]) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._consume_call(node, state)

    def _consume_call(self, node: ast.Call, state: Dict[str, bool]) -> None:
        name = _random_key_arg(node)
        if name is None:
            return
        if state.get(name, False):
            key = (node.lineno, node.col_offset, name)
            if key not in self._reported:
                self._reported.add(key)
                self.findings.append(
                    self.rule.finding(
                        self.module,
                        node,
                        f"PRNG key '{name}' already consumed by an earlier "
                        "jax.random call — split it first",
                    )
                )
        else:
            state[name] = True

    @staticmethod
    def _merge(state: Dict[str, bool], forks: List[Dict[str, bool]]) -> None:
        for name in {n for fork in forks for n in fork}:
            state[name] = all(fork.get(name, False) for fork in forks)


def _assigned_names_of_target(target: ast.expr) -> Set[str]:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


@register
class PrngKeyReuseRule(Rule):
    """NX011: the same PRNG key fed to two ``jax.random.*`` consumers without
    an intervening split/rebind — correlated randomness, the classic silent
    JAX bug."""

    rule_id = "NX011"
    description = "PRNG keys must not be consumed twice"

    def check_module(self, module: Module) -> Iterator[Finding]:
        if module.tree is None:
            return
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                flow = _KeyFlow(self, module)
                flow.run(node)
                yield from flow.findings


# -- NX012 ---------------------------------------------------------------------


def canonical_axes(project: Project) -> Optional[Set[str]]:
    mesh = project.find_module(MESH_PATH)
    if mesh is None or mesh.tree is None:
        return None
    for stmt in mesh.tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if (
            isinstance(target, ast.Name)
            and target.id == "AXIS_ORDER"
            and isinstance(stmt.value, (ast.Tuple, ast.List))
        ):
            return {
                e.value
                for e in stmt.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return None


_SPEC_CALL_NAMES = frozenset({"P", "PartitionSpec"})
_AXIS_KWARGS = frozenset({"axis_name", "axis_names"})


#: annotation name that marks a logical-name -> MESH-AXIS mapping dict
#: (``parallel/sharding.py`` and the serving rule tables): its VALUES are
#: mesh-axis strings and fall under NX012; its KEYS are logical dimension
#: names (any vocabulary) and deliberately do not
_RULETABLE_ANNOTATION = "RuleTable"


@register
class MeshAxisLiteralRule(Rule):
    """NX012: every string literal naming a mesh axis — ``PartitionSpec``/
    ``P`` arguments, ``axis_name=`` kwargs on collectives/shard_map, and
    the VALUES of ``RuleTable``-annotated logical->mesh-axis dicts
    (``parallel/sharding.py``'s tables and the serving rule table that
    tpu_nexus/serving/sharded.py layers on them, ISSUE 13) — must be one
    of the axes declared in ``parallel/mesh.py`` ``AXIS_ORDER``.  A
    typo'd axis string fails only at trace time on a mesh that doesn't
    bind it — or binds the wrong one; a typo'd RULE-TABLE value is worse:
    ``spec_for`` only validates the LOGICAL names, so a bad mesh axis
    sails through to GSPMD."""

    rule_id = "NX012"
    description = "mesh-axis string literals must name axes from parallel/mesh.py"

    def check_project(self, project: Project) -> Iterator[Finding]:
        axes = canonical_axes(project)
        if not axes:
            return
        mesh_module = project.find_module(MESH_PATH)
        for module in project.modules:
            if module.tree is None or module is mesh_module:
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.AnnAssign):
                    yield from self._check_ruletable(module, node, axes)
                if not isinstance(node, ast.Call):
                    continue
                name = _terminal_name(node.func)
                if name in _SPEC_CALL_NAMES:
                    for arg in node.args:
                        yield from self._check_strings(module, arg, axes)
                for kw in node.keywords:
                    if kw.arg in _AXIS_KWARGS:
                        yield from self._check_strings(module, kw.value, axes)

    def _check_ruletable(
        self, module: Module, node: ast.AnnAssign, axes: Set[str]
    ) -> Iterator[Finding]:
        """``NAME: RuleTable = {...}`` — every string in the dict's VALUES
        (bare, or inside a tuple of axes) must be a canonical mesh axis.
        Keys are logical names, not checked.  Non-dict values (an alias of
        another table) are out of scope for a static pass."""
        if _terminal_name(node.annotation) != _RULETABLE_ANNOTATION:
            return
        value = node.value
        if isinstance(value, ast.Dict):
            values = value.values
        else:
            return
        for v in values:
            # a ``{**BASE, "layers": "pp"}`` merge contributes its own
            # literal values; the spread base is checked where IT is
            # defined (same rule, that assignment)
            yield from self._check_strings(module, v, axes)

    def _check_strings(self, module: Module, expr: ast.expr, axes: Set[str]) -> Iterator[Finding]:
        for child in ast.walk(expr):
            if (
                isinstance(child, ast.Constant)
                and isinstance(child.value, str)
                and child.value not in axes
            ):
                yield self.finding(
                    module,
                    child,
                    f"'{child.value}' is not a mesh axis declared in "
                    f"{MESH_PATH} AXIS_ORDER ({', '.join(sorted(axes))})",
                )
