"""CLI driver.

    python -m tools.nxlint tpu_nexus/            # human output, exit 0/1
    python -m tools.nxlint --json tpu_nexus/     # machine output
    python -m tools.nxlint --sarif out.sarif tpu_nexus/   # CI annotators
    python -m tools.nxlint --changed origin/main tpu_nexus/  # pre-commit
    python -m tools.nxlint --write-baseline nxlint-baseline.json tpu_nexus/
    python -m tools.nxlint --baseline nxlint-baseline.json tpu_nexus/

Exit-code contract (same as tools/check_coverage.py): 0 clean, 1 findings,
2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from tools.nxlint.engine import (
    all_rules,
    collect_modules,
    lint_project,
    load_baseline,
    write_baseline,
    Finding,
    Project,
)

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def changed_files(ref: str, root: str) -> set:
    """Repo-relative posix paths touched vs ``ref`` (diff + untracked), for
    ``--changed``.  Raises CalledProcessError when ``ref`` is unknown."""
    diff = subprocess.run(
        ["git", "diff", "--name-only", ref],
        cwd=root,
        check=True,
        capture_output=True,
        text=True,
    )
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=root,
        check=True,
        capture_output=True,
        text=True,
    )
    return {
        line.strip()
        for out in (diff.stdout, untracked.stdout)
        for line in out.splitlines()
        if line.strip()
    }


def sarif_payload(findings, rules) -> dict:
    """Minimal valid SARIF 2.1.0: one run, the rule catalog as
    reportingDescriptors, one result per finding (columns are 1-based in
    SARIF, 0-based in Finding)."""
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "nxlint",
                        "informationUri": "docs/STATIC_ANALYSIS.md",
                        "rules": [
                            {
                                "id": rule.rule_id,
                                "shortDescription": {"text": rule.description},
                            }
                            for rule in rules
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule_id,
                        "level": "error" if f.severity == "error" else "warning",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.file},
                                    "region": {
                                        "startLine": f.line,
                                        "startColumn": f.col + 1,
                                    },
                                }
                            }
                        ],
                        "fingerprints": {"nxlint/v1": f.fingerprint()},
                    }
                    for f in findings
                ],
            }
        ],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.nxlint",
        description="repo-native static analysis for tpu-nexus",
    )
    parser.add_argument("paths", nargs="*", default=["tpu_nexus"], help="files/dirs to lint")
    parser.add_argument("--root", default=".", help="repo root findings are relative to")
    parser.add_argument("--json", action="store_true", dest="as_json", help="JSON output")
    parser.add_argument("--baseline", help="ignore findings fingerprinted in this file")
    parser.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="write current findings as the new baseline and exit 0",
    )
    parser.add_argument(
        "--select",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    parser.add_argument(
        "--changed",
        metavar="REF",
        help="report findings only for files touched vs this git ref "
        "(the whole tree is still scanned so interprocedural rules stay "
        "sound; pre-commit fast path)",
    )
    parser.add_argument(
        "--sarif",
        metavar="FILE",
        help="also write findings as SARIF 2.1.0 to FILE (exit contract unchanged)",
    )
    try:
        args = parser.parse_args(argv)
    except SystemExit as exc:
        return 2 if exc.code not in (0, None) else 0

    rules = all_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.rule_id}  {rule.description}")
        return 0
    if args.select:
        wanted = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in wanted]

    paths = args.paths or ["tpu_nexus"]
    for path in paths:
        if not os.path.exists(path):
            print(f"no such path: {path}", file=sys.stderr)
            return 2

    baseline = None
    if args.baseline:
        if not os.path.isfile(args.baseline):
            print(f"baseline file not found: {args.baseline}", file=sys.stderr)
            return 2
        baseline = load_baseline(args.baseline)

    project = Project(args.root, collect_modules(paths, args.root))

    if args.write_baseline:
        # a baseline snapshot must cover ALL current findings — applying an
        # old baseline here would drop still-present grandfathered findings
        # and resurface them on the next run
        full = lint_project(project, rules=rules)
        write_baseline(args.write_baseline, full)
        print(f"wrote {len(full)} finding(s) to baseline {args.write_baseline}")
        return 0

    findings = lint_project(project, rules=rules, baseline=baseline)

    changed_note = ""
    if args.changed:
        try:
            touched = changed_files(args.changed, args.root)
        except (OSError, subprocess.CalledProcessError) as exc:
            detail = getattr(exc, "stderr", "") or str(exc)
            print(f"--changed {args.changed}: git diff failed: {detail.strip()}", file=sys.stderr)
            return 2
        findings = [f for f in findings if f.file in touched]
        changed_note = f" (changed vs {args.changed}: {len(touched)} file(s))"

    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(sarif_payload(findings, rules), fh, indent=2)
            fh.write("\n")

    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for finding in findings:
            print(finding.render())
        suffix = " (baseline applied)" if baseline else ""
        print(
            f"nxlint: {len(findings)} finding(s) in {len(project.modules)} "
            f"file(s){suffix}{changed_note}"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
