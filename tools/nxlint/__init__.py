"""nxlint — repo-native static analysis for tpu-nexus.

The reference supervisor leans on Go's compiler to keep its control plane
honest; this reproduction is dynamic Python, so the equivalent invariants
(decision-taxonomy totality, CQL schema <-> model parity, tracing-safe JAX
hot paths) are enforced here instead.  Rule catalog and suppression syntax:
docs/STATIC_ANALYSIS.md.

Usage:  python -m tools.nxlint tpu_nexus/
"""

from tools.nxlint.engine import (
    Finding,
    Module,
    Project,
    Rule,
    RuleVisitor,
    all_rules,
    lint_paths,
    lint_project,
    load_baseline,
    register,
)

# importing the rule modules populates the registry (flow carries NX020)
from tools.nxlint import flow  # noqa: F401
from tools.nxlint import rules_concurrency  # noqa: F401
from tools.nxlint import rules_control  # noqa: F401
from tools.nxlint import rules_donation  # noqa: F401
from tools.nxlint import rules_durability  # noqa: F401
from tools.nxlint import rules_envdocs  # noqa: F401
from tools.nxlint import rules_faults  # noqa: F401
from tools.nxlint import rules_handoff  # noqa: F401
from tools.nxlint import rules_pressure  # noqa: F401
from tools.nxlint import rules_serving  # noqa: F401
from tools.nxlint import rules_telemetry  # noqa: F401
from tools.nxlint import rules_tracing  # noqa: F401

__all__ = [
    "Finding",
    "Module",
    "Project",
    "Rule",
    "RuleVisitor",
    "all_rules",
    "lint_paths",
    "lint_project",
    "load_baseline",
    "register",
]
