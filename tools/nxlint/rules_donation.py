"""NX019: buffer-donation safety (ISSUE 16).

``jax.jit(..., donate_argnums=...)`` invalidates the donated device buffer
the moment the call returns: any later use of the old reference raises
``RuntimeError: invalid buffer`` — the DeviceStateLost bug class the
serving engine's swap/rollback machinery exists to avoid.  The safe idiom
is rebinding the donated operand IN the call's own assignment, which is
how every engine dispatch is written::

    next_tokens, self.cache = self._step(self.params, self.cache, ...)

This rule checks that structurally.  Donation SITES are ``jax.jit`` /
``pjit`` calls carrying ``donate_argnums=``, and the engines'
``self._make_jit(fn, donate=...)`` factory seam.  Donated positions
resolve from tuple/int literals in the donate expression (a conditional
``(1,) if tpu else ()`` contributes its literals — may-donate is the
conservative reading), or through a class-level ``self._donate = ...``
assignment.  A donate expression that resolves to no literal positions at
all fails CLOSED as a finding — except when it is itself a parameter of
the enclosing function, which marks a jit FACTORY (the engine
``_make_jit`` body): the obligation belongs to the factory's call sites.

For every call to a donated callable (bound to ``self.X`` and called from
the owning class, or bound to a local name and called in the same scope),
each donated positional argument that is a plain name or ``self.attr``
must be rebound in the call statement's own targets, or never loaded
again in the enclosing scope.  A donated argument that is a PARAMETER of
the enclosing function and dies there moves the obligation one hop up:
callers of that function (resolved through the call graph) are checked
against the same contract at the forwarding position.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, register
from tools.nxlint.flow import CallGraph, FunctionInfo, flow_for, frame_nodes

_JIT_NAMES = frozenset({"jit", "pjit"})
_FACTORY_NAMES = frozenset({"_make_jit"})

#: param-tree transforms whose INPUT becomes stale once the transformed
#: result is installed on device (the quantize-at-swap seam, ISSUE 17)
_TRANSFORM_NAMES = frozenset({"quantize_params"})
#: the device-install seam those transforms feed (PR 11's per-shard
#: ``device_put``); frames that call it are the only scope checked — a
#: gate/test that quantizes a copy AND keeps the bf16 tree is fine
_INSTALL_NAMES = frozenset({"_install_params"})


def _terminal(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _donate_kw(call: ast.Call) -> Optional[ast.expr]:
    name = _terminal(call.func)
    if name in _JIT_NAMES:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                return kw.value
    elif name in _FACTORY_NAMES:
        for kw in call.keywords:
            if kw.arg == "donate":
                return kw.value
    return None


def _literal_positions(expr: ast.expr) -> Set[int]:
    return {
        node.value
        for node in ast.walk(expr)
        if isinstance(node, ast.Constant) and isinstance(node.value, int)
        and not isinstance(node.value, bool)
    }


#: an argument identity we can track across statements: a plain local name
#: or a ``self.attr`` — anything else is a fresh temporary
ArgKey = Tuple[str, str]  # ("name"|"selfattr", identifier)


def _arg_key(expr: ast.expr) -> Optional[ArgKey]:
    if isinstance(expr, ast.Name):
        return ("name", expr.id)
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return ("selfattr", expr.attr)
    return None


def _keys_in(expr: ast.expr, ctx=ast.Load) -> Set[ArgKey]:
    out: Set[ArgKey] = set()
    for node in ast.walk(expr):
        key = _arg_key(node) if isinstance(node, (ast.Name, ast.Attribute)) else None
        if key is not None and isinstance(getattr(node, "ctx", None), ctx):
            out.add(key)
    return out


@register
class DonationSafetyRule(Rule):
    """NX019: a buffer passed to a donated argnum position must be rebound
    by the call statement or never referenced afterwards."""

    rule_id = "NX019"
    description = "donated buffers must not be referenced after the donating call"

    def check_project(self, project: Project) -> Iterator[Finding]:
        try:
            graph = flow_for(project)
        except Exception:  # noqa: BLE001 - no graph, no 1-hop propagation; NX020 reports the breakage
            graph = None
        #: (FunctionInfo qualname) -> [(param position, site description)]
        param_donations: Dict[str, List[Tuple[FunctionInfo, int, str]]] = {}
        for module in project.modules:
            if module.tree is None:
                continue
            yield from self._check_module(module, graph, param_donations)
        if graph is not None:
            yield from self._propagate_one_hop(graph, param_donations)

    # -- per-module pass -------------------------------------------------------

    def _check_module(self, module, graph, param_donations) -> Iterator[Finding]:
        tree = module.tree
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        yield from self._check_install_transforms(module, tree, parents)

        #: id(class node) -> {attr: positions}
        donated_attrs: Dict[int, Dict[str, Set[int]]] = {}
        #: id(scope node) -> {name: positions}
        donated_locals: Dict[int, Dict[str, Set[int]]] = {}

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            donate = _donate_kw(node)
            if donate is None:
                continue
            positions = self._resolve_positions(donate, node, parents)
            if positions is None:
                if self._is_factory_param(donate, node, parents):
                    continue  # the _make_jit body itself: checked at its call sites
                yield self.finding(
                    module,
                    node,
                    "donate expression does not resolve to literal argnum "
                    "positions — NX019 cannot see which buffers this jit "
                    "invalidates (fails closed); use a tuple literal or a "
                    "class-level self._donate assignment",
                )
                continue
            if not positions:
                continue
            target = self._bound_target(node, parents)
            if target is None:
                continue
            kind, name, scope = target
            if kind == "selfattr":
                cls = self._enclosing(parents, node, ast.ClassDef)
                if cls is not None:
                    donated_attrs.setdefault(id(cls), {}).setdefault(name, set()).update(positions)
            else:
                donated_locals.setdefault(id(scope), {}).setdefault(name, set()).update(positions)

        if not donated_attrs and not donated_locals:
            return

        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            cls = self._enclosing(parents, fn, ast.ClassDef)
            attrs = donated_attrs.get(id(cls), {}) if cls is not None else {}
            local_scopes = [donated_locals.get(id(fn), {})]
            for node in frame_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                positions: Optional[Set[int]] = None
                desc = ""
                key = _arg_key(node.func)
                if key is not None and key[0] == "selfattr" and key[1] in attrs:
                    positions = attrs[key[1]]
                    desc = f"self.{key[1]}"
                elif isinstance(node.func, ast.Name):
                    for scope_map in local_scopes:
                        if node.func.id in scope_map:
                            positions = scope_map[node.func.id]
                            desc = node.func.id
                            break
                if positions is None:
                    continue
                yield from self._check_call(
                    module, fn, node, positions, desc, parents, graph, param_donations
                )

    # -- quantize-at-swap transform safety (ISSUE 17) --------------------------

    def _check_install_transforms(self, module, tree, parents) -> Iterator[Finding]:
        """The serving swap seam runs a param-tree transform BETWEEN
        restore and device install::

            params = quantize_params(params, mode=..., group=...)
            ...
            self.params = self._install_params(params)

        Binding the transform result to a FRESH name instead leaves the
        pre-transform host tree live past the install: any later load of
        it works with weights the engine is no longer serving — at best a
        silently-unquantized tree shipped on the next dispatch, at worst
        the use-after-donate ``DeviceStateLost`` class when the install
        path donates the host buffers.  Contract (checked structurally,
        scoped to frames that call ``_install_params``): the transform
        rebinds its own input, or the pre-transform name is never loaded
        after the install statement."""
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            installs = [
                node
                for node in frame_nodes(fn)
                if isinstance(node, ast.Call)
                and _terminal(node.func) in _INSTALL_NAMES
            ]
            if not installs:
                continue
            install = min(installs, key=lambda n: n.lineno)
            install_stmt = self._enclosing_stmt(parents, install)
            for node in frame_nodes(fn):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _terminal(node.value.func) in _TRANSFORM_NAMES
                    and node.value.args
                ):
                    continue
                src = _arg_key(node.value.args[0])
                if src is None:
                    continue
                rebound: Set[ArgKey] = set()
                for target in node.targets:
                    rebound |= _keys_in(target, ctx=(ast.Store,))
                if src in rebound:
                    continue  # the safe idiom: transform over its own input
                after = self._loaded_after(fn, install_stmt, src)
                if after is not None:
                    yield self.finding(
                        module,
                        after,
                        f"{self._key_desc(src)} holds the pre-transform host "
                        f"tree ({_terminal(node.value.func)} at line "
                        f"{node.lineno} bound its result to a fresh name) and "
                        f"is referenced here after _install_params() (line "
                        f"{install.lineno}) shipped the transformed tree to "
                        "device — stale/possibly-donated buffer "
                        "(DeviceStateLost bug class); rebind the transform "
                        "over its input or drop the stale name",
                    )

    # -- donation-site resolution ----------------------------------------------

    def _resolve_positions(
        self, donate: ast.expr, site: ast.AST, parents
    ) -> Optional[Set[int]]:
        positions = _literal_positions(donate)
        if positions:
            return positions
        # empty literal tuple: donation explicitly off
        if isinstance(donate, ast.Tuple) and not donate.elts:
            return set()
        # self._donate: resolve through the class's own assignments, then
        # its (same-module) base classes — the engines assign the policy in
        # _ExecutorCommon and consume it from the concrete executors
        key = _arg_key(donate)
        if key is not None and key[0] == "selfattr":
            cls = self._enclosing(parents, site, ast.ClassDef)
            module_classes = self._module_classes(parents)
            seen: Set[int] = set()
            while cls is not None and id(cls) not in seen:
                seen.add(id(cls))
                found = False
                out: Set[int] = set()
                for node in ast.walk(cls):
                    if (
                        isinstance(node, ast.Assign)
                        and any(_arg_key(t) == key for t in node.targets)
                    ):
                        found = True
                        out.update(_literal_positions(node.value))
                if found:
                    return out
                cls = next(
                    (
                        module_classes.get(base.id)
                        for base in cls.bases
                        if isinstance(base, ast.Name) and base.id in module_classes
                    ),
                    None,
                )
        return None

    @staticmethod
    def _module_classes(parents) -> Dict[str, ast.ClassDef]:
        out: Dict[str, ast.ClassDef] = {}
        for node in parents:
            if isinstance(node, ast.ClassDef):
                out.setdefault(node.name, node)
        return out

    @staticmethod
    def _is_factory_param(donate: ast.expr, site: ast.AST, parents) -> bool:
        if not isinstance(donate, ast.Name):
            return False
        cur = parents.get(site)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = cur.args
                names = {
                    a.arg
                    for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]
                }
                return donate.id in names
            cur = parents.get(cur)
        return False

    def _bound_target(self, call: ast.Call, parents):
        """('selfattr'|'name', identifier, enclosing scope) when the jit
        result is bound — ``self.X = jit(...)`` / ``f = jit(...)``."""
        stmt = parents.get(call)
        if not isinstance(stmt, ast.Assign) or stmt.value is not call:
            return None
        if len(stmt.targets) != 1:
            return None
        target = stmt.targets[0]
        key = _arg_key(target)
        if key is None:
            return None
        scope = self._enclosing(
            parents, stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
        )
        return (key[0], key[1], scope)

    @staticmethod
    def _enclosing(parents, node, kinds):
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = parents.get(cur)
        return None

    # -- call-site safety ------------------------------------------------------

    def _check_call(
        self, module, fn, call, positions, desc, parents, graph, param_donations
    ) -> Iterator[Finding]:
        stmt = self._enclosing_stmt(parents, call)
        rebound: Set[ArgKey] = set()
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                rebound |= _keys_in(target, ctx=(ast.Store,))
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            rebound |= _keys_in(stmt.target, ctx=(ast.Store,))
        param_names = self._param_names(fn)
        for pos in sorted(positions):
            if pos >= len(call.args):
                continue
            key = _arg_key(call.args[pos])
            if key is None:
                continue  # fresh temporary: nothing can reference it later
            if key in rebound:
                continue  # the safe idiom: rebound by the donating statement
            after = self._loaded_after(fn, stmt, key)
            if after is not None:
                yield self.finding(
                    module,
                    after,
                    f"{self._key_desc(key)} was donated to {desc}() at line "
                    f"{call.lineno} (donate position {pos}) and is referenced "
                    "here afterwards — the device buffer is gone "
                    "(DeviceStateLost); rebind it in the donating statement",
                )
            elif key[0] == "name" and key[1] in param_names and graph is not None:
                info = graph.info_for(module, fn)
                if info is not None:
                    param_donations.setdefault(info.qualname, []).append(
                        (info, param_names.index(key[1]), desc)
                    )

    @staticmethod
    def _param_names(fn) -> List[str]:
        args = fn.args
        names = [a.arg for a in [*args.posonlyargs, *args.args]]
        if names and names[0] == "self":
            names = names[1:]
        return names

    def _enclosing_stmt(self, parents, node):
        cur = node
        while cur is not None:
            parent = parents.get(cur)
            if isinstance(cur, ast.stmt):
                return cur
            cur = parent
        return node

    @staticmethod
    def _loaded_after(fn, stmt, key: ArgKey) -> Optional[ast.AST]:
        """First load of ``key`` in ``fn``'s frame after ``stmt`` ends."""
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for node in frame_nodes(fn):
            if getattr(node, "lineno", 0) <= end:
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            if _arg_key(node) == key if isinstance(node, (ast.Name, ast.Attribute)) else False:
                return node
        return None

    @staticmethod
    def _key_desc(key: ArgKey) -> str:
        return f"self.{key[1]}" if key[0] == "selfattr" else f"'{key[1]}'"

    # -- 1-hop propagation -----------------------------------------------------

    def _propagate_one_hop(self, graph: CallGraph, param_donations) -> Iterator[Finding]:
        if not param_donations:
            return
        #: id(def node) -> [(pos, jit desc)]
        by_node: Dict[int, List[Tuple[int, str]]] = {}
        for entries in param_donations.values():
            for info, pos, desc in entries:
                by_node.setdefault(id(info.node), []).append((pos, desc))
        for idx in graph.indexes.values():
            for fn in ast.walk(idx.module.tree):
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for node in frame_nodes(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    for callee, _via in graph.resolve_call(node, idx.module):
                        donated = by_node.get(id(callee.node))
                        if not donated:
                            continue
                        stmt = self._enclosing_stmt(idx.parents, node)
                        rebound: Set[ArgKey] = set()
                        if isinstance(stmt, ast.Assign):
                            for target in stmt.targets:
                                rebound |= _keys_in(target, ctx=(ast.Store,))
                        for pos, desc in donated:
                            if pos >= len(node.args):
                                continue
                            key = _arg_key(node.args[pos])
                            if key is None or key in rebound:
                                continue
                            after = self._loaded_after(fn, stmt, key)
                            if after is not None:
                                yield self.finding(
                                    idx.module,
                                    after,
                                    f"{self._key_desc(key)} is referenced here "
                                    f"after {callee.name}() (line {node.lineno}) "
                                    f"forwarded it to donated jit {desc}() — "
                                    "the device buffer is gone "
                                    "(DeviceStateLost); rebind it in the "
                                    "calling statement",
                                )
        return
