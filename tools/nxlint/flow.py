"""nxflow: repo-wide call-graph construction and interprocedural effect
summaries for nxlint rules (ISSUE 16).

The lexical rules (NX007/NX008/NX010/NX014) go blind the moment a helper
function wraps their sink or barrier — exactly the refactoring pressure
the next roadmap items apply to ``serving/`` and ``workload/``.  This
module gives them eyes across call boundaries:

``CallGraph``
    One per lint run (memoized on the ``Project`` via :func:`flow_for`).
    Resolves call sites to function definitions across every scanned
    module: lexically-scoped names, ``from``-imports, module-alias
    attribute chains (``durability.verify_step(...)``), ``self.method``
    calls through the enclosing class and its bases, and attribute/local
    types inferred from constructor assignments and annotations
    (``ckpt = TensorCheckpointer(...)``; ``reporter: LedgerReporter``).
    Resolution is deliberately conservative: anything dynamic resolves to
    nothing, and rules treat "nothing" per their own fail-open/closed
    contract (NX020 below is the fails-closed backstop).

``CallGraph.summarize``
    Bounded-depth (``MAX_DEPTH`` call hops), cycle-guarded, memoized
    effect summaries.  The cache key is a *deep hash*: the function's own
    body hash combined with its resolved callees' deep hashes — so a
    summary is invalidated the moment the helper's body (or a helper's
    helper's body) changes, and never invalidated by mere line motion.
    Summaries are computed from the raw AST: a ``# nxlint: disable``
    comment suppresses a *finding*, never an *effect* — a sanctioned
    publish seam still summarizes as "publishes", which is what moves the
    barrier obligation to its callers.

``NX020``
    The fails-closed contract for unresolvable dynamic dispatch: inside
    the flow-scoped strict modules (``serving/``, ``workload/``,
    ``checkpoint/``) a ``from x import *`` or a call to a name bound
    nowhere in the module defeats call-graph resolution and is itself a
    finding, so the interprocedural rules can never silently lose
    coverage to an unresolvable edge.

Rule catalog and effect-summary table: docs/STATIC_ANALYSIS.md
("Interprocedural rules").
"""

from __future__ import annotations

import ast
import builtins as _builtins
import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, register

#: maximum number of call hops an effect summary propagates through.  Two
#: hops is the contract (a helper's helper); three keeps one hop of slack
#: for the wrapper-of-wrapper refactors without letting summaries crawl
#: the whole graph.
MAX_DEPTH = 3

_BUILTIN_NAMES = frozenset(dir(_builtins))

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPE_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _dotted_module(rel_path: str) -> str:
    """``tpu_nexus/serving/engine.py`` -> ``tpu_nexus.serving.engine``."""
    path = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    if path.endswith("/__init__"):
        path = path[: -len("/__init__")]
    return path.replace("/", ".")


def frame_nodes(scope: ast.AST) -> List[ast.AST]:
    """All AST nodes executing in ``scope``'s own frame — nested
    function/class/lambda bodies excluded (same semantics as the lexical
    rules' scope walks: an effect inside a nested def that may never run
    proves nothing about the frame)."""
    out: List[ast.AST] = []
    body = scope.body if hasattr(scope, "body") else []
    if not isinstance(body, list):  # Lambda.body is a single expression
        body = [body]
    stack = list(body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, _SCOPE_DEFS):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


@dataclass
class FunctionInfo:
    """One function definition the graph knows about."""

    module: Module
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    name: str
    qualname: str  # "serving/engine.py::ServingEngine.step"
    class_name: Optional[str]  # immediately-enclosing class, if a method

    _body_hash: Optional[str] = None

    @property
    def body_hash(self) -> str:
        """Content hash of this function's own AST (line numbers excluded,
        so renumbering never invalidates a summary but any body edit
        does)."""
        if self._body_hash is None:
            dump = ast.dump(self.node, include_attributes=False)
            self._body_hash = hashlib.sha256(dump.encode("utf-8")).hexdigest()[:16]
        return self._body_hash


class _ModuleIndex:
    """Per-module AST indexes the graph resolves against."""

    def __init__(self, module: Module) -> None:
        self.module = module
        tree = module.tree
        assert tree is not None
        self.dotted = _dotted_module(module.rel_path)
        is_package = module.rel_path.endswith("__init__.py")
        self._package = self.dotted if is_package else self.dotted.rsplit(".", 1)[0] if "." in self.dotted else ""

        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

        #: local alias -> dotted module path (``import a.b as c``; a plain
        #: ``import a.b`` binds the head ``a`` to ``a``)
        self.import_modules: Dict[str, str] = {}
        #: local alias -> (dotted source module, original name)
        self.import_names: Dict[str, Tuple[str, str]] = {}
        self.star_imports: List[ast.ImportFrom] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.import_modules[alias.asname] = alias.name
                    else:
                        head = alias.name.split(".")[0]
                        self.import_modules.setdefault(head, head)
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(node)
                for alias in node.names:
                    if alias.name == "*":
                        self.star_imports.append(node)
                        continue
                    local = alias.asname or alias.name
                    self.import_names[local] = (base, alias.name)

        #: module-level defs and classes (by name)
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        #: every def in the module, nested or not, keyed by node identity
        self.infos: Dict[int, FunctionInfo] = {}
        for node in ast.walk(tree):
            if isinstance(node, _FUNC_DEFS):
                info = FunctionInfo(
                    module=module,
                    node=node,
                    name=node.name,
                    qualname=f"{module.rel_path}::{self._qualname(node)}",
                    class_name=self._enclosing_class_name(node),
                )
                self.infos[id(node)] = info
        for stmt in tree.body:
            if isinstance(stmt, _FUNC_DEFS):
                self.functions[stmt.name] = self.infos[id(stmt)]
            elif isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = stmt

        #: every name the module binds anywhere (assignments, params, defs,
        #: imports, loop/with/except targets, walrus) — the NX020 oracle
        #: for "this call target cannot be a module-local binding"
        self.bound_names: Set[str] = set(_BUILTIN_NAMES)
        for node in ast.walk(tree):
            if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
                self.bound_names.add(node.id)
            elif isinstance(node, ast.arg):
                self.bound_names.add(node.arg)
            elif isinstance(node, (*_FUNC_DEFS, ast.ClassDef)):
                self.bound_names.add(node.name)
            elif isinstance(node, ast.alias):
                self.bound_names.add(node.asname or node.name.split(".")[0])
            elif isinstance(node, ast.ExceptHandler) and node.name:
                self.bound_names.add(node.name)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                self.bound_names.update(node.names)
            elif isinstance(node, ast.MatchAs) and node.name:
                self.bound_names.add(node.name)
            elif isinstance(node, ast.MatchStar) and node.name:
                self.bound_names.add(node.name)

        self._local_defs_cache: Dict[int, Dict[str, FunctionInfo]] = {}

    def _from_base(self, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        parts = self._package.split(".") if self._package else []
        parts = parts[: len(parts) - (node.level - 1)] if node.level > 1 else parts
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts)

    def _qualname(self, node: ast.AST) -> str:
        names = [node.name]
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (*_FUNC_DEFS, ast.ClassDef)):
                names.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(names))

    def _enclosing_class_name(self, node: ast.AST) -> Optional[str]:
        parent = self.parents.get(node)
        return parent.name if isinstance(parent, ast.ClassDef) else None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_DEFS):
                return cur
            cur = self.parents.get(cur)
        return None

    def local_defs(self, scope: ast.AST) -> Dict[str, FunctionInfo]:
        """Functions defined directly in ``scope``'s frame."""
        cached = self._local_defs_cache.get(id(scope))
        if cached is not None:
            return cached
        defs: Dict[str, FunctionInfo] = {}

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_DEFS):
                    defs.setdefault(child.name, self.infos[id(child)])
                    continue
                if isinstance(child, ast.ClassDef):
                    continue
                walk(child)

        walk(scope)
        self._local_defs_cache[id(scope)] = defs
        return defs


def _attr_chain(expr: ast.expr) -> Optional[List[str]]:
    """``self.mgr.allocate`` -> ["self", "mgr", "allocate"]; None when the
    base is not a plain name (a call result, subscript, ...)."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


#: resolution provenance, so rules can filter which edges they trust:
#: "local"  — lexically-scoped def in the same module
#: "import" — from-imported module-level function
#: "module" — module-alias attribute call (``durability.verify_step()``)
#: "self"   — ``self.method()`` through the enclosing class (and bases)
#: "attr"   — ``self.attr.method()`` via a constructor/annotation type
#: "var"    — ``obj.method()`` via a local constructor/annotation type
Resolution = Tuple[FunctionInfo, str]

#: effect-summary cache, shared across CallGraph instances (lint runs in
#: one process).  Keyed by (domain, deep hash, remaining depth): the deep
#: hash folds in every resolved callee's body hash, so editing a helper —
#: at any depth the summary saw — changes the key and forces a recompute.
_SUMMARY_CACHE: Dict[Tuple[str, str, int], object] = {}


def summary_cache_stats() -> Dict[str, int]:
    """For tests: cache size plus cumulative compute count."""
    return {"entries": len(_SUMMARY_CACHE), "computes": _SUMMARY_COMPUTES[0]}


_SUMMARY_COMPUTES = [0]


class CallGraph:
    """Project-wide def/call resolution plus memoized effect summaries."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.indexes: Dict[str, _ModuleIndex] = {}
        self.module_by_dotted: Dict[str, _ModuleIndex] = {}
        for module in project.modules:
            if module.tree is None:
                continue
            idx = _ModuleIndex(module)
            self.indexes[module.rel_path] = idx
            self.module_by_dotted[idx.dotted] = idx
        self.class_index: Dict[str, List[Tuple[_ModuleIndex, ast.ClassDef]]] = {}
        for idx in self.indexes.values():
            for name, cls in idx.classes.items():
                self.class_index.setdefault(name, []).append((idx, cls))
        self._resolve_memo: Dict[Tuple[str, int], List[Resolution]] = {}
        self._inprogress: Dict[str, Set[int]] = {}
        self._deephash_memo: Dict[Tuple[int, int], str] = {}
        self._deephash_inprogress: Set[int] = set()
        self._attr_types_memo: Dict[int, Dict[str, List[Tuple[_ModuleIndex, ast.ClassDef]]]] = {}
        self._var_types_memo: Dict[int, Dict[str, List[Tuple[_ModuleIndex, ast.ClassDef]]]] = {}

    # -- lookups ---------------------------------------------------------------

    def index_for(self, module: Module) -> Optional[_ModuleIndex]:
        return self.indexes.get(module.rel_path)

    def info_for(self, module: Module, node: ast.AST) -> Optional[FunctionInfo]:
        idx = self.indexes.get(module.rel_path)
        return idx.infos.get(id(node)) if idx is not None else None

    def functions(self) -> Iterator[FunctionInfo]:
        for idx in self.indexes.values():
            yield from idx.infos.values()

    # -- call resolution -------------------------------------------------------

    def resolve_call(self, call: ast.Call, module: Module) -> List[Resolution]:
        """Definitions ``call`` can reach, with provenance.  Empty when the
        target is external (jax/numpy/builtins) or dynamic."""
        idx = self.indexes.get(module.rel_path)
        if idx is None:
            return []
        key = (module.rel_path, id(call))
        cached = self._resolve_memo.get(key)
        if cached is not None:
            return cached
        func = call.func
        out: List[Resolution] = []
        if isinstance(func, ast.Name):
            out = self._resolve_name(func.id, call, idx)
        elif isinstance(func, ast.Attribute):
            out = self._resolve_attribute(func, call, idx)
        self._resolve_memo[key] = out
        return out

    def _resolve_name(self, name: str, site: ast.AST, idx: _ModuleIndex) -> List[Resolution]:
        # lexical: enclosing function scopes outward to module level
        node: Optional[ast.AST] = site
        while node is not None:
            if isinstance(node, (*_FUNC_DEFS, ast.Module)):
                found = idx.local_defs(node).get(name)
                if found is not None:
                    via = "local" if not isinstance(node, ast.Module) else "module-def"
                    return [(found, via)]
            node = idx.parents.get(node)
        imported = idx.import_names.get(name)
        if imported is not None:
            base, orig = imported
            target = self.module_by_dotted.get(base)
            if target is not None:
                fn = target.functions.get(orig)
                if fn is not None:
                    return [(fn, "import")]
        return []

    def _resolve_attribute(self, func: ast.Attribute, call: ast.Call, idx: _ModuleIndex) -> List[Resolution]:
        chain = _attr_chain(func)
        if not chain or len(chain) < 2:
            return []
        head, method = chain[0], chain[-1]
        if head == "self":
            cls = idx.enclosing_class(call)
            if cls is None:
                return []
            if len(chain) == 2:  # self.method()
                return [
                    (info, "self")
                    for info in self._lookup_method(idx, cls, method)
                ]
            if len(chain) == 3:  # self.attr.method()
                out: List[Resolution] = []
                for owner_idx, owner_cls in self._self_attr_types(idx, cls).get(chain[1], []):
                    out.extend(
                        (info, "attr")
                        for info in self._lookup_method(owner_idx, owner_cls, method)
                    )
                return out
            return []
        # module-alias chains: ``durability.verify_step()``,
        # ``tpu_nexus.checkpoint.durability.verify_step()``
        resolved_mod = self._module_for_chain(idx, chain[:-1])
        if resolved_mod is not None:
            fn = resolved_mod.functions.get(method)
            return [(fn, "module")] if fn is not None else []
        # instance method through a local variable/parameter type
        if len(chain) == 2:
            out = []
            encl = idx.enclosing_function(call)
            if encl is not None:
                for owner_idx, owner_cls in self._local_var_types(idx, encl).get(head, []):
                    out.extend(
                        (info, "var")
                        for info in self._lookup_method(owner_idx, owner_cls, method)
                    )
            return out
        return []

    def _module_for_chain(self, idx: _ModuleIndex, parts: Sequence[str]) -> Optional[_ModuleIndex]:
        """Resolve ["durability"] or ["tpu_nexus","checkpoint","durability"]
        to a scanned module, through the module's import aliases."""
        if not parts:
            return None
        head = parts[0]
        candidates: List[str] = []
        if head in idx.import_modules:
            candidates.append(".".join([idx.import_modules[head], *parts[1:]]))
        imported = idx.import_names.get(head)
        if imported is not None:
            base, orig = imported
            candidates.append(".".join([base, orig, *parts[1:]] if base else [orig, *parts[1:]]))
        for dotted in candidates:
            target = self.module_by_dotted.get(dotted)
            if target is not None:
                return target
        return None

    def _resolve_class(self, idx: _ModuleIndex, expr: ast.expr) -> List[Tuple[_ModuleIndex, ast.ClassDef]]:
        """The project class(es) a constructor/annotation expression names."""
        chain = _attr_chain(expr) if isinstance(expr, ast.Attribute) else None
        if isinstance(expr, ast.Name):
            local = idx.classes.get(expr.id)
            if local is not None:
                return [(idx, local)]
            imported = idx.import_names.get(expr.id)
            if imported is not None:
                base, orig = imported
                target = self.module_by_dotted.get(base)
                if target is not None and orig in target.classes:
                    return [(target, target.classes[orig])]
            return []
        if chain and len(chain) >= 2:
            target = self._module_for_chain(idx, chain[:-1])
            if target is not None and chain[-1] in target.classes:
                return [(target, target.classes[chain[-1]])]
        return []

    def _lookup_method(
        self,
        idx: _ModuleIndex,
        cls: ast.ClassDef,
        name: str,
        _seen: Optional[Set[int]] = None,
    ) -> List[FunctionInfo]:
        seen = _seen if _seen is not None else set()
        if id(cls) in seen:
            return []
        seen.add(id(cls))
        for stmt in cls.body:
            if isinstance(stmt, _FUNC_DEFS) and stmt.name == name:
                info = idx.infos.get(id(stmt))
                return [info] if info is not None else []
        out: List[FunctionInfo] = []
        for base in cls.bases:
            for base_idx, base_cls in self._resolve_class(idx, base):
                out.extend(self._lookup_method(base_idx, base_cls, name, seen))
        return out

    def _self_attr_types(
        self, idx: _ModuleIndex, cls: ast.ClassDef
    ) -> Dict[str, List[Tuple[_ModuleIndex, ast.ClassDef]]]:
        """``self.X = ClassName(...)`` / class-body ``X: ClassName``
        annotations -> attr name -> candidate classes."""
        cached = self._attr_types_memo.get(id(cls))
        if cached is not None:
            return cached
        types: Dict[str, List[Tuple[_ModuleIndex, ast.ClassDef]]] = {}
        for node in ast.walk(cls):
            target: Optional[ast.expr] = None
            value: Optional[ast.expr] = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.annotation
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                attr = target.attr
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                attr = node.target.id  # class-body annotation
            else:
                continue
            if isinstance(value, ast.Call):
                value = value.func
            if value is not None:
                found = self._resolve_class(idx, value)
                if found:
                    types.setdefault(attr, []).extend(found)
        self._attr_types_memo[id(cls)] = types
        return types

    def _local_var_types(
        self, idx: _ModuleIndex, fn: ast.AST
    ) -> Dict[str, List[Tuple[_ModuleIndex, ast.ClassDef]]]:
        """Constructor assignments and annotations inside one function:
        ``ckpt = TensorCheckpointer(...)``, ``reporter: LedgerReporter``."""
        cached = self._var_types_memo.get(id(fn))
        if cached is not None:
            return cached
        types: Dict[str, List[Tuple[_ModuleIndex, ast.ClassDef]]] = {}
        args = fn.args
        for arg in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
            if arg.annotation is not None:
                found = self._resolve_class(idx, arg.annotation)
                if found:
                    types.setdefault(arg.arg, []).extend(found)
        for node in frame_nodes(fn):
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.annotation
            if not isinstance(target, ast.Name):
                continue
            if isinstance(value, ast.Call):
                value = value.func
            if value is not None:
                found = self._resolve_class(idx, value)
                if found:
                    types.setdefault(target.id, []).extend(found)
        self._var_types_memo[id(fn)] = types
        return types

    # -- effect summaries ------------------------------------------------------

    def deep_hash(self, fn: FunctionInfo, depth: int = MAX_DEPTH) -> str:
        """``fn``'s body hash folded with its resolved callees' deep
        hashes, to ``depth`` hops — the summary-cache key component that
        makes the cache invalidate when a helper's body changes."""
        key = (id(fn.node), depth)
        cached = self._deephash_memo.get(key)
        if cached is not None:
            return cached
        if id(fn.node) in self._deephash_inprogress or depth <= 0:
            return fn.body_hash  # cycle/depth cut: own body only
        self._deephash_inprogress.add(id(fn.node))
        try:
            h = hashlib.sha256(fn.body_hash.encode("utf-8"))
            callees: Dict[str, FunctionInfo] = {}
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    for callee, _via in self.resolve_call(node, fn.module):
                        callees.setdefault(callee.qualname, callee)
            for qualname in sorted(callees):
                h.update(self.deep_hash(callees[qualname], depth - 1).encode("utf-8"))
            digest = h.hexdigest()[:16]
        finally:
            self._deephash_inprogress.discard(id(fn.node))
        self._deephash_memo[key] = digest
        return digest

    def summarize(
        self,
        fn: FunctionInfo,
        domain: str,
        compute: Callable[[FunctionInfo, Callable[[FunctionInfo], object]], object],
        default: object,
        depth: int = MAX_DEPTH,
    ) -> object:
        """Memoized bounded-depth effect summary.  ``compute(fn, recurse)``
        supplies the domain logic; ``recurse(callee)`` yields the callee's
        summary one hop deeper (or ``default`` past the depth bound or on
        a call-graph cycle — the cycle guard is what makes recursion over
        mutually-recursive helpers terminate)."""
        if depth <= 0:
            return default
        inprog = self._inprogress.setdefault(domain, set())
        if id(fn.node) in inprog:
            return default
        key = (domain, self.deep_hash(fn), depth)
        if key in _SUMMARY_CACHE:
            return _SUMMARY_CACHE[key]
        inprog.add(id(fn.node))
        try:
            _SUMMARY_COMPUTES[0] += 1
            value = compute(
                fn, lambda callee: self.summarize(callee, domain, compute, default, depth - 1)
            )
        finally:
            inprog.discard(id(fn.node))
        _SUMMARY_CACHE[key] = value
        return value


def flow_for(project: Project) -> CallGraph:
    """The one CallGraph of this lint run, built on first use and shared
    by every flow-backed rule.  Raises on construction failure — callers
    fall back to their lexical pass (and NX020 reports the breakage)."""
    graph = getattr(project, "_nxflow_graph", None)
    if graph is None:
        error = getattr(project, "_nxflow_error", None)
        if error is not None:
            raise error
        try:
            graph = CallGraph(project)
        except Exception as exc:  # noqa: BLE001 - any graph-build crash must degrade rules to lexical, re-raised for NX020 to report
            project._nxflow_error = exc
            raise
        project._nxflow_graph = graph
    return graph


# -- NX020: the fails-closed contract ------------------------------------------

#: modules whose invariants the flow rules guard: dynamic dispatch the
#: graph cannot resolve is a FINDING here, not a silent coverage hole
_STRICT_FRAGMENTS = (
    "tpu_nexus/serving/",
    "tpu_nexus/workload/",
    "tpu_nexus/checkpoint/",
)


def is_strict_module(rel_path: str) -> bool:
    return any(frag in rel_path for frag in _STRICT_FRAGMENTS)


@register
class FlowIntegrityRule(Rule):
    """NX020: call-graph resolvability inside the flow-scoped strict
    modules (``serving/``, ``workload/``, ``checkpoint/``).  The
    interprocedural rules (NX007/NX008/NX010/NX014/NX017/NX019) are only
    as sound as resolution: a ``from x import *`` makes every imported
    name invisible to the graph, and a call to a name bound nowhere in
    the module (no def, no import, no assignment anywhere — a typo or a
    runtime-injected global) is dynamic dispatch nothing can resolve.
    Both fail CLOSED as named findings instead of silently dropping call
    edges; a genuinely sanctioned dynamic seam takes a per-line
    ``# nxlint: disable=NX020`` with its rationale.  Also surfaces
    call-graph construction failure itself — a crash in flow.py must
    degrade loudly (rules fall back to lexical), never silently."""

    rule_id = "NX020"
    description = (
        "flow-scoped modules must stay call-graph resolvable "
        "(no star imports or unbound call targets)"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        try:
            graph = flow_for(project)
        except Exception as exc:  # noqa: BLE001 - ANY graph-build failure becomes the named fails-closed finding below
            for module in project.modules:
                if module.tree is not None:
                    yield self.finding(
                        module,
                        module.tree,
                        f"call-graph construction failed ({type(exc).__name__}: "
                        f"{exc}) — interprocedural rules degraded to their "
                        "lexical fallbacks",
                    )
                    return
            return
        for idx in graph.indexes.values():
            if not is_strict_module(idx.module.rel_path):
                continue
            for star in idx.star_imports:
                yield self.finding(
                    idx.module,
                    star,
                    "star import defeats call-graph resolution in a "
                    "flow-scoped module — import names explicitly so "
                    "interprocedural rules can see through them",
                )
            if idx.star_imports:
                continue  # unbound-name checks would all be false positives
            for node in ast.walk(idx.module.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id not in idx.bound_names
                ):
                    yield self.finding(
                        idx.module,
                        node,
                        f"call to '{node.func.id}' resolves to no binding in "
                        "this module (unresolvable dynamic dispatch in a "
                        "flow-scoped module) — define/import it, or mark a "
                        "sanctioned dynamic seam with a justified disable",
                    )
