"""Checkpoint-durability rules: the publish-after-durability invariant the
restart-from-step contract depends on.

NX007  tensor-checkpoint publish discipline: any code that writes
       ``tensor_checkpoint_uri`` to the ledger must be lexically preceded,
       in the same function scope, by a durability barrier on the
       checkpointer (``commit()`` / ``verify()`` / a verified-step
       resolution).  The bug class: ``harness.py`` used to publish the URI
       right after ``ckpt.save()`` — Orbax saves may be async, so a
       preemption mid-save stranded the watchdog's restart on a torn step
       the ledger swore was there.

NX008  params hot-swap discipline (the NX007 contract's serving mirror,
       ISSUE 9): any ``swap_params(...)`` call site must be lexically
       preceded, in the same function scope, by a verified-step resolution
       (``restore_params`` / ``latest_verified_step`` / ``verify_step`` /
       ...).  The bug class: a rolling update that loads the newest step
       by mtime and swaps it into a live engine — a torn or bit-rotten
       candidate would be served to every post-swap request with no error
       anywhere.
"""

from __future__ import annotations

import ast
from collections import namedtuple
from typing import Iterator, List, Optional, Set, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, register
from tools.nxlint.flow import CallGraph, flow_for

#: ledger-publisher calls (method name, last attribute segment).  These are
#: the ONLY sanctioned ways to write tensor_checkpoint_uri; their own
#: definitions (on LedgerReporter) are the sinks and are exempted below —
#: the barrier obligation sits with every CALLER.  ``health_rollback`` is
#: the health-policy recovery's repoint (ISSUE 10) — same contract: the
#: caller's ``latest_verified_step(before=...)`` resolution is the barrier.
_PUBLISHER_CALLS = frozenset(
    {"tensor_checkpoint", "checkpoint_rollback", "health_rollback"}
)

#: function definitions that ARE the publisher (LedgerReporter methods):
#: their bodies write the column by construction; flagging them would force
#: a vacuous barrier inside the sink
_PUBLISHER_DEFS = frozenset(_PUBLISHER_CALLS)

#: names that prove a durability barrier ran: TensorCheckpointer.commit /
#: verify, the verified-step resolutions (latest_verified_step,
#: durability.verify_step / newest_verified_step), and the watchdog's
#: injected resolver (referenced through asyncio.to_thread, so bare
#: references count, not just calls).  ``wait``/``wait_until_finished``
#: are deliberately ABSENT: draining the async orbax write commits no
#: manifest — ``save(); wait(); publish()`` is exactly the torn-URI bug
#: class this rule exists to stop (and the names are too generic anyway:
#: an unrelated ``event.wait()`` must not silence the rule)
_BARRIER_NAMES = frozenset(
    {
        "commit",
        "verify",
        "verify_step",
        "latest_verified_step",
        "newest_verified_step",
        "resolve_verified_uri",
        "_resolve_verified_uri",
    }
)

#: the ledger column the rule guards
_URI_KEY = "tensor_checkpoint_uri"


def _last_segment(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _writes_uri_key(call: ast.Call) -> bool:
    """True when any argument of ``call`` contains a dict literal with the
    ``tensor_checkpoint_uri`` key — a DIRECT column write
    (``update_fields``/``_guarded_update``/``compare_and_set``/raw upsert)
    bypassing the sanctioned publishers."""
    for arg in (*call.args, *(kw.value for kw in call.keywords)):
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Dict):
                for key in sub.keys:
                    if isinstance(key, ast.Constant) and key.value == _URI_KEY:
                        return True
    return False


def _scope_statements(scope: ast.AST) -> List[ast.AST]:
    """Nodes executing in ``scope``'s own frame: nested function/class
    bodies excluded (a barrier inside a nested def that may never run
    proves nothing).  A ``Lambda`` scope's frame is its single body
    expression."""
    out: List[ast.AST] = []
    body = scope.body if hasattr(scope, "body") else []
    if not isinstance(body, list):  # Lambda.body is one expression node
        body = [body]
    stack = list(body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


# -- the interprocedural leg (ISSUE 16) ----------------------------------------

#: per-function effect summary for one barrier domain.  ``has_barrier``:
#: the body references a barrier name (or calls a helper that does), so a
#: call to this function counts as a barrier at the call site.
#: ``unbarriered_sink``: the body reaches a sink with no preceding barrier
#: (or IS a sanctioned sink def), so a call to this function inherits the
#: sink's obligation — the caller must barrier first.
_BarrierSummary = namedtuple("_BarrierSummary", "has_barrier unbarriered_sink")
_NEUTRAL = _BarrierSummary(False, False)


class _BarrierFlow:
    """Flow context for one (module, domain): classifies resolved calls as
    barrier-equivalent or sink-equivalent via bounded-depth summaries.

    Summaries are computed on the raw AST — a ``# nxlint: disable`` on a
    wrapper's body suppresses the wrapper's own finding (the sanctioned
    seam) but never hides the effect, which is exactly how the barrier
    obligation moves to the wrapper's callers."""

    def __init__(
        self,
        graph: CallGraph,
        module: Module,
        domain: str,
        sink_names: frozenset,
        sink_defs: frozenset,
        barrier_names: frozenset,
        check_uri_key: bool,
    ) -> None:
        self.graph = graph
        self.module = module
        self.domain = domain
        self.sink_names = sink_names
        self.sink_defs = sink_defs
        self.barrier_names = barrier_names
        self.check_uri_key = check_uri_key

    def _is_sink_call(self, node: ast.Call) -> bool:
        if _last_segment(node.func) in self.sink_names:
            return True
        return self.check_uri_key and _writes_uri_key(node)

    def _compute(self, fn, recurse) -> _BarrierSummary:
        sink_lines: List[int] = []
        barrier_lines: Set[int] = set()
        for node in _scope_statements(fn.node):
            if isinstance(node, ast.Call):
                end = getattr(node, "end_lineno", None) or node.lineno
                if self._is_sink_call(node):
                    sink_lines.append(end)
                else:
                    for callee, _via in self.graph.resolve_call(node, fn.module):
                        sub = recurse(callee)
                        if sub.unbarriered_sink:
                            sink_lines.append(end)
                        elif sub.has_barrier:
                            barrier_lines.add(node.lineno)
            if isinstance(node, (ast.Attribute, ast.Name)):
                if _last_segment(node) in self.barrier_names:
                    barrier_lines.add(node.lineno)
        if fn.name in self.sink_defs:
            # the sanctioned sink itself: callers inherit the obligation
            return _BarrierSummary(has_barrier=False, unbarriered_sink=True)
        has_barrier = bool(barrier_lines)
        unbarriered = any(
            not any(b <= line for b in barrier_lines) for line in sink_lines
        )
        return _BarrierSummary(has_barrier and not unbarriered, unbarriered)

    def _summary(self, callee) -> _BarrierSummary:
        return self.graph.summarize(callee, self.domain, self._compute, _NEUTRAL)

    def classify_call(self, node: ast.Call) -> Tuple[bool, Optional[str]]:
        """(counts as barrier, sink label) for a call that is NOT itself a
        lexical sink — resolved through the call graph."""
        is_barrier = False
        sink_label: Optional[str] = None
        for callee, _via in self.graph.resolve_call(node, self.module):
            sub = self._summary(callee)
            if sub.unbarriered_sink and sink_label is None:
                sink_label = (
                    f"{_last_segment(node.func) or callee.name}() "
                    f"(reaches a {'/'.join(sorted(self.sink_names))} sink "
                    f"through {callee.name})"
                )
            if sub.has_barrier:
                is_barrier = True
        return is_barrier, sink_label

    def alias_names(self, scope: ast.AST) -> Set[str]:
        """Bound-method aliases of a sink in this frame:
        ``publish = reporter.tensor_checkpoint`` — the classic lexical
        blind spot (the later ``publish(uri, step)`` carries no sink
        name)."""
        out: Set[str] = set()
        for node in _scope_statements(scope):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr in self.sink_names
            ):
                out.add(node.targets[0].id)
        return out


def _publishers_and_barriers(
    scope: ast.AST,
    flow: Optional[_BarrierFlow] = None,
) -> Tuple[List[Tuple[ast.Call, str]], Set[int]]:
    """(publisher calls with a label, line numbers where a barrier name is
    referenced) within the scope's own frame.  With ``flow``, calls that
    RESOLVE to a helper summarized as barrier/sink count too."""
    publishers: List[Tuple[ast.Call, str]] = []
    barrier_lines: Set[int] = set()
    aliases = flow.alias_names(scope) if flow is not None else set()
    for node in _scope_statements(scope):
        if isinstance(node, ast.Call):
            name = _last_segment(node.func)
            if name in _PUBLISHER_CALLS:
                publishers.append((node, f"{name}()"))
            elif _writes_uri_key(node):
                publishers.append((node, f"direct {_URI_KEY} write via {name or 'call'}()"))
            elif isinstance(node.func, ast.Name) and node.func.id in aliases:
                publishers.append(
                    (node, f"{name}() (a bound alias of a ledger publisher)")
                )
            elif flow is not None:
                is_barrier, sink_label = flow.classify_call(node)
                if sink_label is not None:
                    publishers.append((node, sink_label))
                elif is_barrier:
                    barrier_lines.add(node.lineno)
        # barrier: a call OR reference (asyncio.to_thread(self._resolver, ...)
        # passes the barrier as an argument) to a barrier-named attribute
        if isinstance(node, (ast.Attribute, ast.Name)):
            if _last_segment(node) in _BARRIER_NAMES:
                barrier_lines.add(node.lineno)
    return publishers, barrier_lines


class _DurabilityVisitor(ast.NodeVisitor):
    def __init__(
        self,
        rule: "CheckpointPublishBarrierRule",
        module: Module,
        flow: Optional[_BarrierFlow] = None,
    ) -> None:
        self.rule = rule
        self.module = module
        self.flow = flow
        self.findings: List[Finding] = []

    def _check_scope(self, scope: ast.AST, scope_name: Optional[str]) -> None:
        publishers, barrier_lines = _publishers_and_barriers(scope, self.flow)
        if not publishers:
            return
        if scope_name in _PUBLISHER_DEFS:
            return  # the sink itself; the obligation sits with its callers
        for call, label in publishers:
            # <= end_lineno: a barrier anywhere within the publish call's
            # own span counts — the barrier IS the argument
            # (reporter.tensor_checkpoint(ckpt.commit(step), step)), which
            # is maximally safe, and a formatter may wrap that argument
            # onto a line after the call's header
            last_line = getattr(call, "end_lineno", None) or call.lineno
            if not any(line <= last_line for line in barrier_lines):
                self.findings.append(
                    self.rule.finding(
                        self.module,
                        call,
                        f"{label} publishes {_URI_KEY} with no preceding "
                        "durability barrier in this scope — call "
                        "TensorCheckpointer.commit()/verify()/"
                        "latest_verified_step() first so the ledger never "
                        "points at an uncommitted or corrupt step",
                    )
                )

    def visit_Module(self, node: ast.Module) -> None:
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda body cannot hold statements, but it CAN hold a publish —
        # `cb = lambda: reporter.tensor_checkpoint(uri, step)` — and the
        # fail-closed contract must see it.  The barrier search runs over
        # the same single expression: only an inline barrier (e.g. the uri
        # coming straight out of ckpt.commit(step)) passes.
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        # class bodies execute at definition time — same frame rules apply
        self._check_scope(node, node.name)
        self.generic_visit(node)


#: the hot-swap sinks: installing weights into a live executor/engine.
#: Their own definitions are exempt (the engine method calling the executor
#: method is the sink chain, not a call site needing its own barrier).
_SWAP_CALLS = frozenset({"swap_params"})
_SWAP_DEFS = frozenset(_SWAP_CALLS)

#: names that prove the swapped params came out of a VERIFIED checkpoint
#: step.  ``restore_params`` belongs here even though NX007 omits it: its
#: contract IS verify-first (``TensorCheckpointer._resolve_step`` verifies
#: before Orbax touches a byte), and it is the one call every honest swap
#: path makes.  ``commit`` is deliberately ABSENT: committing step N proves
#: nothing about the (possibly different, possibly rotten) step being
#: swapped in.
_SWAP_BARRIER_NAMES = frozenset(
    {
        "verify",
        "verify_step",
        "latest_verified_step",
        "newest_verified_step",
        "resolve_verified_uri",
        "_resolve_verified_uri",
        "restore_params",
    }
)


def _swaps_and_barriers(
    scope: ast.AST,
    flow: Optional[_BarrierFlow] = None,
) -> Tuple[List[Tuple[ast.Call, str]], Set[int]]:
    """(swap call sites with a label, line numbers where a verified-step
    resolution is referenced) within the scope's own frame.  With
    ``flow``, calls resolving to a helper summarized as verified-step
    resolution / swap wrapper count too."""
    swaps: List[Tuple[ast.Call, str]] = []
    barrier_lines: Set[int] = set()
    aliases = flow.alias_names(scope) if flow is not None else set()
    for node in _scope_statements(scope):
        if isinstance(node, ast.Call):
            name = _last_segment(node.func)
            if name in _SWAP_CALLS:
                swaps.append((node, "swap_params()"))
            elif isinstance(node.func, ast.Name) and node.func.id in aliases:
                swaps.append((node, f"{name}() (a bound alias of swap_params)"))
            elif flow is not None:
                is_barrier, sink_label = flow.classify_call(node)
                if sink_label is not None:
                    swaps.append((node, sink_label))
                elif is_barrier:
                    barrier_lines.add(node.lineno)
        if isinstance(node, (ast.Attribute, ast.Name)):
            if _last_segment(node) in _SWAP_BARRIER_NAMES:
                barrier_lines.add(node.lineno)
    return swaps, barrier_lines


class _SwapVisitor(ast.NodeVisitor):
    def __init__(
        self,
        rule: "ParamsSwapBarrierRule",
        module: Module,
        flow: Optional[_BarrierFlow] = None,
    ) -> None:
        self.rule = rule
        self.module = module
        self.flow = flow
        self.findings: List[Finding] = []

    def _check_scope(self, scope: ast.AST, scope_name: Optional[str]) -> None:
        swaps, barrier_lines = _swaps_and_barriers(scope, self.flow)
        if not swaps:
            return
        if scope_name in _SWAP_DEFS:
            return  # the sink chain itself; the obligation sits with callers
        for call, label in swaps:
            # <= end_lineno, same rationale as NX007: the barrier may BE an
            # argument of the swap call, possibly formatter-wrapped —
            # engine.swap_params(ckpt.restore_params(step)) is maximally safe
            last_line = getattr(call, "end_lineno", None) or call.lineno
            if not any(line <= last_line for line in barrier_lines):
                self.findings.append(
                    self.rule.finding(
                        self.module,
                        call,
                        f"{label} installs weights with no preceding "
                        "verified-step resolution in this scope — resolve "
                        "the step first (restore_params()/"
                        "latest_verified_step()/verify_step()) so a live "
                        "engine can never serve an unverified checkpoint",
                    )
                )

    def visit_Module(self, node: ast.Module) -> None:
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # `cb = lambda: engine.swap_params(params)` must not dodge the rule
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)


def _graph_or_none(rule: Rule, project: Project) -> Optional[CallGraph]:
    """The shared CallGraph, or None — rules degrade to their lexical pass
    when flow is disabled (tests pin each pass separately) or the graph
    failed to build (NX020 reports that loudly)."""
    if not getattr(rule, "flow_enabled", True):
        return None
    try:
        return flow_for(project)
    except Exception:  # noqa: BLE001 - fallback contract: ANY graph failure degrades to lexical; NX020 owns reporting it
        return None


@register
class ParamsSwapBarrierRule(Rule):
    """NX008: live-engine weight swaps only behind a verified-step
    resolution.  Fails closed: EVERY call spelled ``*.swap_params(...)`` is
    flagged unless a verified-step-resolution name lexically precedes it in
    the same function scope — and, through the call graph (ISSUE 16), so
    is any call RESOLVING to a helper that wraps the swap (including a
    bound-method alias), while a call to a helper whose body performs the
    verified-step resolution counts as the barrier.  With flow disabled or
    broken the rule degrades to the pure lexical pass (the repo-clean gate
    plus the rollout chaos drills cover the dynamic side; this rule stops
    the honest mistake of swapping whatever ``latest_step()`` returned)."""

    rule_id = "NX008"
    description = "swap_params call sites need a preceding verified-step resolution"
    flow_enabled = True

    def _flow(self, project: Project, module: Module) -> Optional[_BarrierFlow]:
        graph = _graph_or_none(self, project)
        if graph is None:
            return None
        return _BarrierFlow(
            graph,
            module,
            domain="nx008",
            sink_names=_SWAP_CALLS,
            sink_defs=_SWAP_DEFS,
            barrier_names=_SWAP_BARRIER_NAMES,
            check_uri_key=False,
        )

    def check_project(self, project: Project) -> Iterator[Finding]:
        for module in project.modules:
            if module.tree is None:
                continue
            visitor = _SwapVisitor(self, module, self._flow(project, module))
            visitor.visit(module.tree)
            yield from visitor.findings


@register
class CheckpointPublishBarrierRule(Rule):
    """NX007: the ledger's ``tensor_checkpoint_uri`` may only be written
    behind a durability barrier.  Fails closed: every call spelled like a
    publisher (``.tensor_checkpoint(...)``, ``.checkpoint_rollback(...)``,
    or any call passing a dict literal with the ``tensor_checkpoint_uri``
    key) is flagged unless a barrier-named call/reference lexically precedes
    it in the same function scope.  The interprocedural leg (ISSUE 16)
    extends both sides through the call graph: a call resolving to a
    helper that publishes without its own barrier (or to a bound alias of
    a publisher) inherits the obligation at the CALL SITE, and a call to a
    helper whose body runs the barrier counts as the barrier.  With flow
    disabled or broken the rule degrades to the pure lexical pass —
    deliberately conservative static analysis either way; the repo-clean
    gate plus the chaos drills (tests/test_checkpoint_chaos) cover the
    dynamic side."""

    rule_id = "NX007"
    description = "tensor_checkpoint_uri writes need a preceding durability barrier"
    flow_enabled = True

    def _flow(self, project: Project, module: Module) -> Optional[_BarrierFlow]:
        graph = _graph_or_none(self, project)
        if graph is None:
            return None
        return _BarrierFlow(
            graph,
            module,
            domain="nx007",
            sink_names=_PUBLISHER_CALLS,
            sink_defs=_PUBLISHER_DEFS,
            barrier_names=_BARRIER_NAMES,
            check_uri_key=True,
        )

    def check_project(self, project: Project) -> Iterator[Finding]:
        for module in project.modules:
            if module.tree is None:
                continue
            visitor = _DurabilityVisitor(self, module, self._flow(project, module))
            visitor.visit(module.tree)
            yield from visitor.findings
