"""Checkpoint-durability rules: the publish-after-durability invariant the
restart-from-step contract depends on.

NX007  tensor-checkpoint publish discipline: any code that writes
       ``tensor_checkpoint_uri`` to the ledger must be lexically preceded,
       in the same function scope, by a durability barrier on the
       checkpointer (``commit()`` / ``verify()`` / a verified-step
       resolution).  The bug class: ``harness.py`` used to publish the URI
       right after ``ckpt.save()`` — Orbax saves may be async, so a
       preemption mid-save stranded the watchdog's restart on a torn step
       the ledger swore was there.

NX008  params hot-swap discipline (the NX007 contract's serving mirror,
       ISSUE 9): any ``swap_params(...)`` call site must be lexically
       preceded, in the same function scope, by a verified-step resolution
       (``restore_params`` / ``latest_verified_step`` / ``verify_step`` /
       ...).  The bug class: a rolling update that loads the newest step
       by mtime and swaps it into a live engine — a torn or bit-rotten
       candidate would be served to every post-swap request with no error
       anywhere.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from tools.nxlint.engine import Finding, Module, Rule, register

#: ledger-publisher calls (method name, last attribute segment).  These are
#: the ONLY sanctioned ways to write tensor_checkpoint_uri; their own
#: definitions (on LedgerReporter) are the sinks and are exempted below —
#: the barrier obligation sits with every CALLER.  ``health_rollback`` is
#: the health-policy recovery's repoint (ISSUE 10) — same contract: the
#: caller's ``latest_verified_step(before=...)`` resolution is the barrier.
_PUBLISHER_CALLS = frozenset(
    {"tensor_checkpoint", "checkpoint_rollback", "health_rollback"}
)

#: function definitions that ARE the publisher (LedgerReporter methods):
#: their bodies write the column by construction; flagging them would force
#: a vacuous barrier inside the sink
_PUBLISHER_DEFS = frozenset(_PUBLISHER_CALLS)

#: names that prove a durability barrier ran: TensorCheckpointer.commit /
#: verify, the verified-step resolutions (latest_verified_step,
#: durability.verify_step / newest_verified_step), and the watchdog's
#: injected resolver (referenced through asyncio.to_thread, so bare
#: references count, not just calls).  ``wait``/``wait_until_finished``
#: are deliberately ABSENT: draining the async orbax write commits no
#: manifest — ``save(); wait(); publish()`` is exactly the torn-URI bug
#: class this rule exists to stop (and the names are too generic anyway:
#: an unrelated ``event.wait()`` must not silence the rule)
_BARRIER_NAMES = frozenset(
    {
        "commit",
        "verify",
        "verify_step",
        "latest_verified_step",
        "newest_verified_step",
        "resolve_verified_uri",
        "_resolve_verified_uri",
    }
)

#: the ledger column the rule guards
_URI_KEY = "tensor_checkpoint_uri"


def _last_segment(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _writes_uri_key(call: ast.Call) -> bool:
    """True when any argument of ``call`` contains a dict literal with the
    ``tensor_checkpoint_uri`` key — a DIRECT column write
    (``update_fields``/``_guarded_update``/``compare_and_set``/raw upsert)
    bypassing the sanctioned publishers."""
    for arg in (*call.args, *(kw.value for kw in call.keywords)):
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Dict):
                for key in sub.keys:
                    if isinstance(key, ast.Constant) and key.value == _URI_KEY:
                        return True
    return False


def _scope_statements(scope: ast.AST) -> List[ast.AST]:
    """Nodes executing in ``scope``'s own frame: nested function/class
    bodies excluded (a barrier inside a nested def that may never run
    proves nothing).  A ``Lambda`` scope's frame is its single body
    expression."""
    out: List[ast.AST] = []
    body = scope.body if hasattr(scope, "body") else []
    if not isinstance(body, list):  # Lambda.body is one expression node
        body = [body]
    stack = list(body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _publishers_and_barriers(
    scope: ast.AST,
) -> Tuple[List[Tuple[ast.Call, str]], Set[int]]:
    """(publisher calls with a label, line numbers where a barrier name is
    referenced) within the scope's own frame."""
    publishers: List[Tuple[ast.Call, str]] = []
    barrier_lines: Set[int] = set()
    for node in _scope_statements(scope):
        if isinstance(node, ast.Call):
            name = _last_segment(node.func)
            if name in _PUBLISHER_CALLS:
                publishers.append((node, f"{name}()"))
            elif _writes_uri_key(node):
                publishers.append((node, f"direct {_URI_KEY} write via {name or 'call'}()"))
        # barrier: a call OR reference (asyncio.to_thread(self._resolver, ...)
        # passes the barrier as an argument) to a barrier-named attribute
        if isinstance(node, (ast.Attribute, ast.Name)):
            if _last_segment(node) in _BARRIER_NAMES:
                barrier_lines.add(node.lineno)
    return publishers, barrier_lines


class _DurabilityVisitor(ast.NodeVisitor):
    def __init__(self, rule: "CheckpointPublishBarrierRule", module: Module) -> None:
        self.rule = rule
        self.module = module
        self.findings: List[Finding] = []

    def _check_scope(self, scope: ast.AST, scope_name: Optional[str]) -> None:
        publishers, barrier_lines = _publishers_and_barriers(scope)
        if not publishers:
            return
        if scope_name in _PUBLISHER_DEFS:
            return  # the sink itself; the obligation sits with its callers
        for call, label in publishers:
            # <= end_lineno: a barrier anywhere within the publish call's
            # own span counts — the barrier IS the argument
            # (reporter.tensor_checkpoint(ckpt.commit(step), step)), which
            # is maximally safe, and a formatter may wrap that argument
            # onto a line after the call's header
            last_line = getattr(call, "end_lineno", None) or call.lineno
            if not any(line <= last_line for line in barrier_lines):
                self.findings.append(
                    self.rule.finding(
                        self.module,
                        call,
                        f"{label} publishes {_URI_KEY} with no preceding "
                        "durability barrier in this scope — call "
                        "TensorCheckpointer.commit()/verify()/"
                        "latest_verified_step() first so the ledger never "
                        "points at an uncommitted or corrupt step",
                    )
                )

    def visit_Module(self, node: ast.Module) -> None:
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda body cannot hold statements, but it CAN hold a publish —
        # `cb = lambda: reporter.tensor_checkpoint(uri, step)` — and the
        # fail-closed contract must see it.  The barrier search runs over
        # the same single expression: only an inline barrier (e.g. the uri
        # coming straight out of ckpt.commit(step)) passes.
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        # class bodies execute at definition time — same frame rules apply
        self._check_scope(node, node.name)
        self.generic_visit(node)


#: the hot-swap sinks: installing weights into a live executor/engine.
#: Their own definitions are exempt (the engine method calling the executor
#: method is the sink chain, not a call site needing its own barrier).
_SWAP_CALLS = frozenset({"swap_params"})
_SWAP_DEFS = frozenset(_SWAP_CALLS)

#: names that prove the swapped params came out of a VERIFIED checkpoint
#: step.  ``restore_params`` belongs here even though NX007 omits it: its
#: contract IS verify-first (``TensorCheckpointer._resolve_step`` verifies
#: before Orbax touches a byte), and it is the one call every honest swap
#: path makes.  ``commit`` is deliberately ABSENT: committing step N proves
#: nothing about the (possibly different, possibly rotten) step being
#: swapped in.
_SWAP_BARRIER_NAMES = frozenset(
    {
        "verify",
        "verify_step",
        "latest_verified_step",
        "newest_verified_step",
        "resolve_verified_uri",
        "_resolve_verified_uri",
        "restore_params",
    }
)


def _swaps_and_barriers(scope: ast.AST) -> Tuple[List[ast.Call], Set[int]]:
    """(swap_params call sites, line numbers where a verified-step
    resolution is referenced) within the scope's own frame."""
    swaps: List[ast.Call] = []
    barrier_lines: Set[int] = set()
    for node in _scope_statements(scope):
        if isinstance(node, ast.Call) and _last_segment(node.func) in _SWAP_CALLS:
            swaps.append(node)
        if isinstance(node, (ast.Attribute, ast.Name)):
            if _last_segment(node) in _SWAP_BARRIER_NAMES:
                barrier_lines.add(node.lineno)
    return swaps, barrier_lines


class _SwapVisitor(ast.NodeVisitor):
    def __init__(self, rule: "ParamsSwapBarrierRule", module: Module) -> None:
        self.rule = rule
        self.module = module
        self.findings: List[Finding] = []

    def _check_scope(self, scope: ast.AST, scope_name: Optional[str]) -> None:
        swaps, barrier_lines = _swaps_and_barriers(scope)
        if not swaps:
            return
        if scope_name in _SWAP_DEFS:
            return  # the sink chain itself; the obligation sits with callers
        for call in swaps:
            # <= end_lineno, same rationale as NX007: the barrier may BE an
            # argument of the swap call, possibly formatter-wrapped —
            # engine.swap_params(ckpt.restore_params(step)) is maximally safe
            last_line = getattr(call, "end_lineno", None) or call.lineno
            if not any(line <= last_line for line in barrier_lines):
                self.findings.append(
                    self.rule.finding(
                        self.module,
                        call,
                        "swap_params() installs weights with no preceding "
                        "verified-step resolution in this scope — resolve "
                        "the step first (restore_params()/"
                        "latest_verified_step()/verify_step()) so a live "
                        "engine can never serve an unverified checkpoint",
                    )
                )

    def visit_Module(self, node: ast.Module) -> None:
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # `cb = lambda: engine.swap_params(params)` must not dodge the rule
        self._check_scope(node, None)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._check_scope(node, node.name)
        self.generic_visit(node)


@register
class ParamsSwapBarrierRule(Rule):
    """NX008: live-engine weight swaps only behind a verified-step
    resolution.  Fails closed: EVERY call spelled ``*.swap_params(...)`` is
    flagged unless a verified-step-resolution name lexically precedes it in
    the same function scope (same conservative lexical analysis as NX007 —
    the repo-clean gate plus the rollout chaos drills cover the dynamic
    side; this rule stops the honest mistake of swapping whatever
    ``latest_step()`` returned)."""

    rule_id = "NX008"
    description = "swap_params call sites need a preceding verified-step resolution"

    def check_module(self, module: Module) -> Iterator[Finding]:
        if module.tree is None:
            return
        visitor = _SwapVisitor(self, module)
        visitor.visit(module.tree)
        yield from visitor.findings


@register
class CheckpointPublishBarrierRule(Rule):
    """NX007: the ledger's ``tensor_checkpoint_uri`` may only be written
    behind a durability barrier.  Fails closed: every call spelled like a
    publisher (``.tensor_checkpoint(...)``, ``.checkpoint_rollback(...)``,
    or any call passing a dict literal with the ``tensor_checkpoint_uri``
    key) is flagged unless a barrier-named call/reference lexically precedes
    it in the same function scope.  Lexical-precedence is deliberately
    conservative static analysis — a barrier on a dead branch passes, but
    the repo-clean gate plus the chaos drills (tests/test_checkpoint_chaos)
    cover the dynamic side; this rule stops the honest mistake of
    publishing right after ``save()``."""

    rule_id = "NX007"
    description = "tensor_checkpoint_uri writes need a preceding durability barrier"

    def check_module(self, module: Module) -> Iterator[Finding]:
        if module.tree is None:
            return
        visitor = _DurabilityVisitor(self, module)
        visitor.visit(module.tree)
        yield from visitor.findings
