"""Serving-engine rules: the request-lifecycle invariants the engine's
retirement path depends on.

NX005  request-state totality (serving/request.py + serving/engine.py)
NX006  serving except discipline: every handler re-raises, classifies
       through supervisor.taxonomy, or carries a BLE001 justification
NX013  drafter parity coverage: every Drafter registered in
       serving/speculative.py DRAFTERS must be named by a test under
       tests/ (the NX009 fails-closed pattern — an undrilled drafter is
       an unproven acceptance oracle)
NX014  no blocking host readback in the engine dispatch loop: step
       results materialize ONLY inside the sanctioned deferred seam
       (``_materialize*`` methods) — one stray ``np.asarray``/``.item()``
       between dispatches silently re-serializes the overlapped engine
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional, Set, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, RuleVisitor, register
from tools.nxlint.flow import CallGraph, FunctionInfo, flow_for
from tools.nxlint.rules_control import _attr_names, _module_assign

REQUEST_PATH = "serving/request.py"
ENGINE_PATH = "serving/engine.py"
STATE_CLASS = "RequestState"


def _state_constants(class_node: ast.ClassDef) -> Dict[str, ast.AST]:
    constants: Dict[str, ast.AST] = {}
    for stmt in class_node.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.value, ast.Constant):
            targets = [stmt.target]
        else:
            continue
        for target in targets:
            if isinstance(target, ast.Name) and target.id.isupper():
                constants[target.id] = stmt
    return constants


def _dict_rows(value: ast.AST, owner: str) -> Optional[Dict[str, Tuple[ast.AST, Set[str]]]]:
    """``{Owner.KEY: <expr>, ...}`` -> key name -> (key node, Owner.* names
    referenced in the row's value).  None when the node is not a dict."""
    if not isinstance(value, ast.Dict):
        return None
    rows: Dict[str, Tuple[ast.AST, Set[str]]] = {}
    for key, val in zip(value.keys, value.values):
        if key is None:
            continue
        for name in _attr_names(key, owner):
            rows[name] = (key, _attr_names(val, owner))
    return rows


@register
class RequestStateTotalityRule(Rule):
    """NX005: the serving request lifecycle must be TOTAL — every
    ``RequestState`` constant has a ``TRANSITIONS`` row and belongs to
    exactly one of ``TERMINAL_STATES`` / ``ACTIVE_STATES``; terminal means
    exactly "no outgoing transitions"; and every terminal state has a row
    in the engine's ``RETIREMENT_ACTIONS`` dispatch.  The NX001
    taxonomy-totality pattern applied to the serving engine: an unmapped
    state is the bug class where retirement raises KeyError mid-request
    (or a request wedges in a state nothing ever retires)."""

    rule_id = "NX005"
    description = "serving request-state machine must be total over RequestState"

    def check_project(self, project: Project) -> Iterator[Finding]:
        module = project.find_module(REQUEST_PATH)
        if module is None or module.tree is None:
            return
        class_node = next(
            (
                n
                for n in module.tree.body
                if isinstance(n, ast.ClassDef) and n.name == STATE_CLASS
            ),
            None,
        )
        if class_node is None:
            yield self.finding(
                module, module.tree, f"{STATE_CLASS} class not found in {module.rel_path}"
            )
            return
        constants = _state_constants(class_node)

        transitions_node = _module_assign(module.tree, "TRANSITIONS")
        transitions = (
            None if transitions_node is None else _dict_rows(transitions_node, STATE_CLASS)
        )
        if transitions is None:
            yield self.finding(
                module,
                transitions_node or module.tree,
                "TRANSITIONS table not found (or not a dict literal)",
            )

        partitions: Dict[str, Optional[Tuple[ast.AST, Set[str]]]] = {}
        for table in ("TERMINAL_STATES", "ACTIVE_STATES"):
            value = _module_assign(module.tree, table)
            if value is None:
                yield self.finding(module, module.tree, f"required table {table} not found")
                partitions[table] = None
            else:
                partitions[table] = (value, _attr_names(value, STATE_CLASS))

        terminal = partitions.get("TERMINAL_STATES")
        active = partitions.get("ACTIVE_STATES")

        for name, node in sorted(constants.items()):
            if transitions is not None and name not in transitions:
                yield self.finding(
                    module, node, f"{STATE_CLASS}.{name} has no TRANSITIONS row"
                )
            if terminal is not None and active is not None:
                in_terminal = name in terminal[1]
                in_active = name in active[1]
                if not in_terminal and not in_active:
                    yield self.finding(
                        module,
                        node,
                        f"{STATE_CLASS}.{name} is in neither TERMINAL_STATES nor "
                        "ACTIVE_STATES (lifecycle undeclared)",
                    )
                elif in_terminal and in_active:
                    yield self.finding(
                        module,
                        node,
                        f"{STATE_CLASS}.{name} is in both TERMINAL_STATES and "
                        "ACTIVE_STATES",
                    )
                # terminal <=> no outgoing transitions: a terminal state with
                # successors can be resurrected; an active state without any
                # is a wedge nothing ever retires
                if transitions is not None and name in transitions:
                    outgoing = transitions[name][1]
                    if in_terminal and outgoing:
                        yield self.finding(
                            module,
                            transitions[name][0],
                            f"terminal state {STATE_CLASS}.{name} declares outgoing "
                            f"transitions {sorted(outgoing)}",
                        )
                    if in_active and not in_terminal and not outgoing:
                        yield self.finding(
                            module,
                            transitions[name][0],
                            f"active state {STATE_CLASS}.{name} has no outgoing "
                            "transitions (unretirable dead end)",
                        )

        # stale references: table members that no longer name a constant
        if transitions is not None:
            for name in sorted(set(transitions) - set(constants)):
                yield self.finding(
                    module,
                    transitions[name][0],
                    f"TRANSITIONS references unknown {STATE_CLASS}.{name}",
                )
            for name, (key_node, targets) in sorted(transitions.items()):
                for target in sorted(targets - set(constants)):
                    yield self.finding(
                        module,
                        key_node,
                        f"TRANSITIONS[{name}] references unknown {STATE_CLASS}.{target}",
                    )
        for table in ("TERMINAL_STATES", "ACTIVE_STATES"):
            payload = partitions.get(table)
            if payload is None:
                continue
            for name in sorted(payload[1] - set(constants)):
                yield self.finding(
                    module, payload[0], f"{table} references unknown {STATE_CLASS}.{name}"
                )

        # -- engine side: retirement dispatch totality over terminal states
        engine = project.find_module(ENGINE_PATH)
        if engine is None or engine.tree is None:
            yield self.finding(
                module,
                module.tree,
                f"{ENGINE_PATH} not found — retirement-dispatch totality unverifiable",
            )
            return
        actions_node = _module_assign(engine.tree, "RETIREMENT_ACTIONS")
        actions = None if actions_node is None else _dict_rows(actions_node, STATE_CLASS)
        if actions is None:
            # fail CLOSED: a renamed dispatch table must not silently skip
            # the totality comparison (same contract as NX002's values dict)
            yield self.finding(
                engine,
                actions_node or engine.tree,
                "RETIREMENT_ACTIONS dict not found (retirement totality unverifiable)",
            )
            return
        terminal_names = terminal[1] if terminal is not None else set()
        for name in sorted(terminal_names - set(actions)):
            yield self.finding(
                engine,
                actions_node,
                f"terminal state {STATE_CLASS}.{name} has no RETIREMENT_ACTIONS row",
            )
        for name in sorted(set(actions) - terminal_names):
            what = "non-terminal" if name in constants else "unknown"
            yield self.finding(
                engine,
                actions[name][0],
                f"RETIREMENT_ACTIONS has a row for {what} state {STATE_CLASS}.{name}",
            )


# -- NX006: serving except discipline ------------------------------------------

#: module path fragments the rule covers: the serving data plane and its
#: workload loop — exactly where a swallowed exception strands requests in
#: non-terminal states with no recorded cause
_NX006_SCOPES = ("serving/", "workload/serve.py")

#: exception types that ARE a recovery-layer product: catching them means
#: the fault already went through supervisor.taxonomy (serving/recovery.py)
_CLASSIFIED_TYPES = frozenset({"StepFault", "DeviceStateLost"})

#: call names (last attribute segment) that classify through the taxonomy
_CLASSIFIER_CALLS = frozenset(
    {"classify", "classify_tpu_failure", "classify_step_fault"}
)

_NX006_JUSTIFICATION_RE = re.compile(r"#\s*noqa:\s*BLE001\s*-\s*\S")


def _last_segment(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _type_names(type_node: Optional[ast.expr]) -> Set[str]:
    if type_node is None:
        return set()
    if isinstance(type_node, ast.Tuple):
        return {_last_segment(e) for e in type_node.elts}
    return {_last_segment(type_node)}


#: scopes whose bodies do NOT execute as part of the handler — a `raise`
#: (or classifier call) inside a nested def/lambda/class proves nothing
#: about what the handler itself does with the caught exception
_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _handler_nodes(stmts) -> "list[ast.AST]":
    """All AST nodes that execute IN the handler's own scope (nested
    function/class bodies excluded)."""
    out = []
    stack = list(stmts)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, _NESTED_SCOPES):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


class _ServingExceptVisitor(RuleVisitor):
    def _clause_text(self, node: ast.ExceptHandler) -> str:
        last = node.lineno
        if node.type is not None:
            last = getattr(node.type, "end_lineno", None) or node.lineno
        return "\n".join(
            self.module.line_text(line) for line in range(node.lineno, last + 1)
        )

    def _compliant(self, node: ast.ExceptHandler) -> bool:
        nodes = _handler_nodes(node.body)
        # 1. re-raise on some path of the handler ITSELF (a raise tucked
        # inside a nested def that may never run doesn't count)
        if any(isinstance(n, ast.Raise) for n in nodes):
            return True
        # 2. the caught types are ALL taxonomy-classification products —
        # `except (StepFault, OSError)` must not ride StepFault's pass,
        # because the OSError leg still swallows unclassified
        caught = _type_names(node.type)
        if caught and caught <= _CLASSIFIED_TYPES:
            return True
        # 3. the handler classifies the CAUGHT exception: a classifier-named
        # call whose arguments reference the `as` name (directly or wrapped,
        # e.g. str(exc)).  `label = model.classify(doc)` on unrelated data
        # is not an escape; neither is any call when nothing was bound.
        if node.name:
            for child in nodes:
                if (
                    isinstance(child, ast.Call)
                    and _last_segment(child.func) in _CLASSIFIER_CALLS
                    and any(
                        isinstance(sub, ast.Name) and sub.id == node.name
                        for arg in (*child.args, *(kw.value for kw in child.keywords))
                        for sub in ast.walk(arg)
                    )
                ):
                    return True
        # 4. explicit justification on the clause line(s)
        return bool(_NX006_JUSTIFICATION_RE.search(self._clause_text(node)))

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if not self._compliant(node):
            what = (
                "bare except"
                if node.type is None
                else f"except {ast.unparse(node.type)}"
            )
            self.report(
                node,
                f"{what} in serving code neither re-raises, classifies via "
                "supervisor.taxonomy, nor carries a '# noqa: BLE001 - "
                "<reason>' justification (a swallowed fault strands "
                "requests without a terminal state)",
            )
        self.generic_visit(node)


@register
class ServingExceptDisciplineRule(Rule):
    """NX006: the serving data plane must never swallow an exception
    silently.  Every ``except`` handler in ``tpu_nexus/serving/`` and
    ``workload/serve.py`` — broad OR narrow — must (a) re-raise on some
    path, (b) classify through ``supervisor.taxonomy`` (call a
    ``classify*`` function, or catch the already-classified ``StepFault``),
    or (c) carry the repo's ``# noqa: BLE001 - <reason>`` justification.
    Fail-closed by construction: a handler is flagged unless it PROVES one
    of the three escapes; the repo-clean gate in
    tests/test_static_analysis.py keeps the shipped tree at zero."""

    rule_id = "NX006"
    description = "serving except handlers must re-raise, classify, or justify"

    def check_module(self, module: Module) -> Iterator[Finding]:
        if module.tree is None:
            return
        if not any(scope in module.rel_path for scope in _NX006_SCOPES):
            return
        visitor = _ServingExceptVisitor(self, module)
        visitor.visit(module.tree)
        yield from visitor.findings


# -- NX013: drafter parity coverage --------------------------------------------

SPECULATIVE_PATH = "serving/speculative.py"
DRAFTER_REGISTRY = "DRAFTERS"


def registered_drafters(tree: ast.Module) -> Dict[str, ast.AST]:
    """Drafter name -> the AST node declaring it: string keys of the
    module-level ``DRAFTERS`` dict literal (possibly annotated).  Non-
    literal keys are deliberately NOT resolved — the registry's contract
    (documented at the assignment) is literal keys precisely so this rule
    can read it as plain AST."""
    drafters: Dict[str, ast.AST] = {}
    for stmt in tree.body:
        value = None
        if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == DRAFTER_REGISTRY for t in stmt.targets
        ):
            value = stmt.value
        elif (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
            and stmt.target.id == DRAFTER_REGISTRY
        ):
            value = stmt.value
        if isinstance(value, ast.Dict):
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    drafters.setdefault(key.value, key)
    return drafters


@register
class DrafterParityRule(Rule):
    """NX013: a registered drafter nobody tests is an acceptance oracle
    nobody has proven.  The speculative engine's safety argument is
    "accepted stream == one-shot greedy generate" — per DRAFTER, because
    each drafter exercises a different acceptance/rollback pattern (ngram
    pads weak guesses, a model drafter replays its own cache) — so every
    ``DRAFTERS`` entry in serving/speculative.py must be named by at
    least one test under tests/.  Literal-string approximation and
    fails-closed semantics exactly mirror NX009 (rules_faults.py): an
    unrecognizable registry shape or a missing tests/ directory is itself
    a finding."""

    rule_id = "NX013"
    description = "every registered Drafter must be named by a test under tests/"

    def check_project(self, project: Project) -> Iterator[Finding]:
        import os

        from tools.nxlint.rules_faults import TESTS_DIR, _test_corpus

        module = project.find_module(SPECULATIVE_PATH)
        if module is None or module.tree is None:
            return  # project doesn't contain the serving tree (tools subtree)
        drafters = registered_drafters(module.tree)
        if not drafters:
            yield self.finding(
                module,
                module.tree,
                f"no {DRAFTER_REGISTRY} registry found in {module.rel_path} "
                "— the drafter extraction no longer matches the registry "
                "shape (rule fails closed; fix registered_drafters)",
            )
            return
        corpus = _test_corpus(project.root)
        if corpus is None:
            yield self.finding(
                module,
                module.tree,
                f"no test files found under {os.path.join(project.root, TESTS_DIR)} "
                "— drafter parity coverage unverifiable (rule fails closed)",
            )
            return
        for name in sorted(drafters):
            if f'"{name}"' in corpus or f"'{name}'" in corpus:
                continue
            yield self.finding(
                module,
                drafters[name],
                f"drafter '{name}' is registered but no test under "
                f"{TESTS_DIR}/ names it — add a parity test (accepted "
                "stream must equal one-shot greedy generate) exercising "
                "the drafter",
            )


# -- NX014: no blocking readback in the engine dispatch loop --------------------

OVERLAP_PATH = "serving/overlap.py"
#: the sharded executors (ISSUE 13) are ALSO whole-module in scope: their
#: contract is that params/cache only ever move host->device or
#: device->device (per-shard device_put at construction and at the
#: swap_params seam) — one stray readback there is a fleet-wide host
#: GATHER of a sharded param tree during a rolling update
SHARDED_PATH = "serving/sharded.py"
#: the observability layer (ISSUE 14) is whole-module in scope too: the
#: tracer/recorder hooks run INSIDE the dispatch loop on every step, so a
#: readback there would re-serialize the overlapped engine exactly like
#: one in the engine itself — the layer's contract is that it records
#: host ints the engine already owned, never device values
TRACING_PATH = "serving/tracing.py"
#: the pressure plane (ISSUE 15) shares the tracing layer's contract:
#: LoadSnapshot/SloMonitor consume materialized host state only — a
#: readback there would serialize every snapshot/observe call against the
#: device, exactly the perturbation the monitor-on/off identity tests
#: exist to rule out
LOADSTATS_PATH = "serving/loadstats.py"
ENGINE_CLASS = "ServingEngine"

#: the sanctioned deferred-materialize seam: functions whose name carries
#: this prefix own the engine's ONLY blocking readback of step results
MATERIALIZE_PREFIX = "_materialize"

#: method names that force a device value to host (block the dispatcher)
_BLOCKING_METHODS = frozenset({"item", "block_until_ready"})

#: numpy module aliases whose ``asarray`` IS a host readback of a device
#: array (``jnp.asarray`` is a device-side convert — a dispatch INPUT —
#: and deliberately not matched)
_NP_MODULES = frozenset({"np", "numpy", "onp"})


def _blocking_readback(node: ast.Call) -> Optional[str]:
    """Human-readable name of the blocking-readback call, or None."""
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in _BLOCKING_METHODS:
            return f".{func.attr}()"
        if func.attr == "device_get":
            return "device_get"
        if (
            func.attr == "asarray"
            and isinstance(func.value, ast.Name)
            and func.value.id in _NP_MODULES
        ):
            return "np.asarray"
    elif isinstance(func, ast.Name) and func.id == "device_get":
        return "device_get"
    return None


@register
class DispatchLoopReadbackRule(Rule):
    """NX014: the engine's dispatch loop must never block on step results
    outside the sanctioned deferred-materialize seam.  The overlapped
    engine's whole value is that the host schedules step N+1 while step
    N's tokens are in flight — ONE stray ``np.asarray`` / ``.item()`` /
    ``jax.device_get`` / ``.block_until_ready()`` between dispatches
    re-serializes it silently (the bench regresses, nothing errors).
    Scope: every method of ``ServingEngine`` (serving/engine.py) plus all
    of serving/overlap.py (the pending-step bookkeeping, which holds
    device handles and must treat them as opaque) plus all of
    serving/sharded.py (ISSUE 13: the shard-aware swap path must land
    weights per-shard — a readback there is a host GATHER of sharded
    params mid-rollout) plus all of serving/tracing.py and
    serving/loadstats.py (ISSUES 14/15: the observability and pressure
    layers record host state the engine already owned, never device
    values); the seam is any function named
    ``_materialize*``.  The executors' synchronous entry points
    (``step``/``begin``/``verify``) are deliberately OUT of scope: they
    ARE the blocking oracle path the parity tests pin everything
    against.  Fails closed when the engine class disappears."""

    rule_id = "NX014"
    description = (
        "no blocking host readback on step results in the engine dispatch "
        "loop outside the _materialize* seam"
    )
    flow_enabled = True

    #: resolution edges the readback summary follows: plain functions
    #: within the serving package (the dispatch plane — including modules
    #: like serving/metrics.py that the lexical scope list never reads)
    #: plus the engine's OWN self-methods.  Method calls on OTHER objects
    #: — ``executor.step(...)``, ``drafter.propose(...)`` — are
    #: deliberately NOT followed: the executors' synchronous entry points
    #: ARE the blocking oracle path (see class docstring).  Helpers
    #: outside serving/ (``build_mesh``'s host-side device-list
    #: ``np.asarray``, config parsing) are construction-time utilities,
    #: not step-result readbacks, and are not followed either.
    @staticmethod
    def _follow(callee: FunctionInfo, via: str) -> bool:
        if "serving/" not in callee.module.rel_path:
            return False
        if via in ("local", "module-def", "import", "module"):
            return True
        return via == "self" and callee.class_name == ENGINE_CLASS

    def _readback_summary(self, graph: CallGraph, callee: FunctionInfo) -> bool:
        def compute(fn: FunctionInfo, recurse) -> bool:
            if fn.name.startswith(MATERIALIZE_PREFIX):
                return False  # the sanctioned seam owns its readbacks
            stack = list(fn.node.body)
            while stack:
                node = stack.pop()
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and node.name.startswith(MATERIALIZE_PREFIX):
                    continue
                if isinstance(node, ast.Call):
                    if _blocking_readback(node) is not None:
                        return True
                    for sub, via in graph.resolve_call(node, fn.module):
                        if self._follow(sub, via) and recurse(sub):
                            return True
                stack.extend(ast.iter_child_nodes(node))
            return False

        return bool(graph.summarize(callee, "nx014", compute, False))

    def check_project(self, project: Project) -> Iterator[Finding]:
        graph = None
        if self.flow_enabled:
            try:
                graph = flow_for(project)
            except Exception:  # noqa: BLE001 - fallback contract: graph failure degrades to lexical; NX020 reports it
                graph = None
        for module in project.modules:
            if module.tree is None:
                continue
            if (
                module.rel_path.endswith(OVERLAP_PATH)
                or module.rel_path.endswith(SHARDED_PATH)
                or module.rel_path.endswith(TRACING_PATH)
                or module.rel_path.endswith(LOADSTATS_PATH)
            ):
                yield from self._scan(module, module.tree.body, graph)
                continue
            if not module.rel_path.endswith(ENGINE_PATH):
                continue
            engine_cls = next(
                (
                    n
                    for n in module.tree.body
                    if isinstance(n, ast.ClassDef) and n.name == ENGINE_CLASS
                ),
                None,
            )
            if engine_cls is None:
                # fail CLOSED: a renamed engine class must not silently drop
                # the dispatch loop out of coverage (NX005's contract)
                yield self.finding(
                    module,
                    module.tree,
                    f"{ENGINE_CLASS} class not found in {module.rel_path} — "
                    "dispatch-loop readback discipline unverifiable",
                )
                continue
            yield from self._scan(module, engine_cls.body, graph)

    def _scan(self, module: Module, stmts, graph: Optional[CallGraph]) -> Iterator[Finding]:
        stack = list(stmts)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name.startswith(MATERIALIZE_PREFIX):
                continue  # the sanctioned seam owns its readbacks
            if isinstance(node, ast.Call):
                what = _blocking_readback(node)
                if what is not None:
                    yield self.finding(
                        module,
                        node,
                        f"blocking host readback ({what}) in the engine "
                        "dispatch loop — step results may only materialize "
                        f"inside a {MATERIALIZE_PREFIX}* method (the "
                        "deferred seam); anything else silently "
                        "re-serializes the overlapped engine",
                    )
                elif graph is not None:
                    # the interprocedural leg (ISSUE 16): a helper wrapping
                    # the readback — in this module or any other — is the
                    # same serialization, one call hop away
                    for callee, via in graph.resolve_call(node, module):
                        if self._follow(callee, via) and self._readback_summary(
                            graph, callee
                        ):
                            yield self.finding(
                                module,
                                node,
                                f"call to {callee.name}() performs a blocking "
                                "host readback (through the call graph) in "
                                "the engine dispatch loop — step results may "
                                f"only materialize inside a "
                                f"{MATERIALIZE_PREFIX}* method (the deferred "
                                "seam); anything else silently re-serializes "
                                "the overlapped engine",
                            )
                            break
            stack.extend(ast.iter_child_nodes(node))
