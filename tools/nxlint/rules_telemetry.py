"""Telemetry rules: the metric-name registry the docs table is built from.

NX015  metric-name parity: every literal metric name emitted through a
       ``Metrics``-shaped receiver in ``tpu_nexus/serving/`` and
       ``tpu_nexus/workload/`` must have a row in
       ``core/telemetry.METRIC_NAMES`` — and every registry row must
       still be emitted somewhere in scope.  The docs table is GENERATED
       from the registry (``python -m tools.metrics_table``), so both
       directions together mean the table can never drift from the code:
       an undocumented metric fails the gate, and so does a documented
       ghost nothing emits any more.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, register

TELEMETRY_PATH = "core/telemetry.py"
REGISTRY_NAME = "METRIC_NAMES"

#: module path fragments in scope: the serving data plane and the workload
#: loops — exactly where the dashboards' metric contract is produced
_NX015_SCOPES = ("tpu_nexus/serving/", "tpu_nexus/workload/")

#: the Metrics interface verbs (core/telemetry.Metrics)
_VERBS = frozenset({"count", "gauge", "histogram", "timing"})

#: receiver terminal names that carry a ``Metrics``-shaped object in the
#: scoped modules (``self._m`` in ServingMetrics, ``self._metrics`` in the
#: fleet controller and HealthMonitor, the harness's ``telemetry``, a bare
#: ``metrics``/``statsd`` local).  A new receiver spelling outside this
#: set silently escapes the rule — keep it in sync when adding one (the
#: repo-clean gate's review is the backstop), but DON'T widen it to "any
#: attribute": ``itertools.count(1)`` and ``list.count(x)`` are the false
#: positives this set exists to exclude.
_RECEIVERS = frozenset({"_m", "_metrics", "metrics", "telemetry", "statsd"})


def _terminal(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def registered_metrics(tree: ast.Module) -> Optional[Dict[str, ast.AST]]:
    """Metric name -> declaring key node: the literal string keys of the
    module-level ``METRIC_NAMES`` dict (possibly annotated).  None when
    the registry assignment is missing or not a dict literal (the rule
    fails closed on that)."""
    for stmt in tree.body:
        value = None
        if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == REGISTRY_NAME for t in stmt.targets
        ):
            value = stmt.value
        elif (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
            and stmt.target.id == REGISTRY_NAME
        ):
            value = stmt.value
        if isinstance(value, ast.Dict):
            names: Dict[str, ast.AST] = {}
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    names.setdefault(key.value, key)
            return names
    return None


def _emission_sites(tree: ast.Module) -> List[Tuple[ast.Call, Optional[str]]]:
    """Every ``<receiver>.<verb>(first_arg, ...)`` call on a Metrics-shaped
    receiver: ``(call node, literal name or None)`` — None flags a
    non-literal first argument (unverifiable against the registry)."""
    sites: List[Tuple[ast.Call, Optional[str]]] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _VERBS
            and _terminal(node.func.value) in _RECEIVERS
        ):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            sites.append((node, node.args[0].value))
        else:
            sites.append((node, None))
    return sites


@register
class MetricNameParityRule(Rule):
    """NX015: a metric a dashboard cannot find is a metric that does not
    exist, and a documented metric nothing emits is worse — an on-call
    building an alert on air.  Every literal metric name emitted via the
    ``Metrics`` verbs in ``tpu_nexus/serving/`` and ``tpu_nexus/workload/``
    must appear in ``core/telemetry.METRIC_NAMES`` (the single registry
    the docs table is generated from), every registry row must still be
    emitted, and a NON-literal metric name in scope is itself a finding
    (the registry cannot vouch for a name computed at runtime).  Fails
    closed when the registry is missing or unparseable — the same
    contract as NX005/NX009/NX013."""

    rule_id = "NX015"
    description = (
        "every emitted metric name must appear in core/telemetry.METRIC_NAMES "
        "(and vice versa)"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        registry_module = project.find_module(TELEMETRY_PATH)
        if registry_module is None or registry_module.tree is None:
            return  # project doesn't contain the core tree (tools subtree)
        registry = registered_metrics(registry_module.tree)
        if registry is None:
            yield self.finding(
                registry_module,
                registry_module.tree,
                f"no {REGISTRY_NAME} dict literal found in "
                f"{registry_module.rel_path} — metric-name parity "
                "unverifiable (rule fails closed; fix registered_metrics "
                "or restore the registry)",
            )
            return
        emitted: Dict[str, List[Tuple[Module, ast.Call]]] = {}
        for module in project.modules:
            if module.tree is None:
                continue
            if not any(scope in module.rel_path for scope in _NX015_SCOPES):
                continue
            for call, name in _emission_sites(module.tree):
                if name is None:
                    yield self.finding(
                        module,
                        call,
                        "metric emitted with a non-literal name — the "
                        f"{REGISTRY_NAME} registry (and the generated docs "
                        "table) cannot vouch for a name computed at "
                        "runtime; use a literal, or split per-variant "
                        "literals",
                    )
                    continue
                emitted.setdefault(name, []).append((module, call))
                if name not in registry:
                    yield self.finding(
                        module,
                        call,
                        f"metric '{name}' is emitted but has no "
                        f"{REGISTRY_NAME} row in {TELEMETRY_PATH} — add it "
                        "(and regenerate the docs table: python -m "
                        "tools.metrics_table --write docs/SERVING.md)",
                    )
        for name in sorted(set(registry) - set(emitted)):
            yield self.finding(
                registry_module,
                registry[name],
                f"{REGISTRY_NAME} documents '{name}' but nothing in "
                f"{' / '.join(_NX015_SCOPES)} emits it any more — remove "
                "the row (and regenerate the docs table) or restore the "
                "emission",
            )
