"""Disaggregated-serving handoff rules (ISSUE 20): the role/decision
contracts behind ``serving/handoff.py``.

NX022  handoff decision totality: the KV-handoff decision tables in
       ``tpu_nexus/serving/handoff.py`` must be TOTAL over the declared
       role and fault-cause spaces — the NX001/NX021 taxonomy pattern
       carried into the disaggregation layer:

       (a) ``HANDOFF_DECISIONS`` (nested ``{role: {cause: action}}``)
       must have an outer key for EVERY member of ``REPLICA_ROLES`` and,
       under each role, an inner key for EVERY member of
       ``HANDOFF_FAULT_CAUSES`` — a new replica role or transfer-fault
       cause without a declared re-placement decision is a static-
       analysis error, not a midnight KeyError halfway through a KV
       handoff;

       (b) ``HANDOFF_CAUSE_ACTIONS`` (``{cause: DecisionAction}``) must
       be total over ``HANDOFF_FAULT_CAUSES`` the same way, so every
       transfer fault classifies to a taxonomy action the supervisor's
       ``SERVING_POD_RECOVERY`` table already covers (NX001 holds the
       other end).

       Keys resolve against the module's string constants or spell the
       strings literally.  Fails CLOSED: a missing or unparseable
       ``handoff.py``, a missing/unresolvable roles or causes tuple, or
       a table that is not a dict literal each yield a finding — an
       unverifiable decision surface is treated as a broken one.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from tools.nxlint.engine import Finding, Module, Project, Rule, register
from tools.nxlint.rules_pressure import (
    _module_assignment,
    _module_string_constants,
    _resolve_key,
)

HANDOFF_PATH = "tpu_nexus/serving/handoff.py"
ROLES_NAME = "REPLICA_ROLES"
CAUSES_NAME = "HANDOFF_FAULT_CAUSES"

#: the decision tables NX022 governs.  ``nested`` marks the role×cause
#: table; flat tables are total over the causes tuple alone.  A new
#: role- or cause-keyed table in handoff.py belongs in this tuple (the
#: repo-clean gate's review is the backstop, as with NX015/NX021).
HANDOFF_TABLES = (
    ("HANDOFF_DECISIONS", True),
    ("HANDOFF_CAUSE_ACTIONS", False),
)


def resolved_tuple(
    tree: ast.Module, name: str, constants: Dict[str, str]
) -> Optional[Set[str]]:
    """The declared string space of one module-level tuple; None when the
    tuple is missing or any element fails to resolve (fails closed)."""
    value = _module_assignment(tree, name)
    if not isinstance(value, (ast.Tuple, ast.List)):
        return None
    out: Set[str] = set()
    for element in value.elts:
        resolved = _resolve_key(element, constants)
        if resolved is None:
            return None
        out.add(resolved)
    return out or None


@register
class HandoffContractRule(Rule):
    """NX022 (module doc): handoff decision tables total over
    REPLICA_ROLES x HANDOFF_FAULT_CAUSES."""

    rule_id = "NX022"
    description = (
        "KV-handoff decision tables (HANDOFF_DECISIONS/"
        "HANDOFF_CAUSE_ACTIONS) total over REPLICA_ROLES x "
        "HANDOFF_FAULT_CAUSES"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        module = project.find_module(HANDOFF_PATH)
        if module is None:
            anchor = project.find_module("tpu_nexus/serving/engine.py")
            if anchor is None:
                return  # project doesn't contain the serving tree (tools subtree)
            yield self.finding(
                anchor,
                anchor.tree or ast.Module(body=[], type_ignores=[]),
                f"{HANDOFF_PATH} missing — the disaggregated-serving "
                "handoff decision tables are unverifiable (rule fails "
                "closed; restore the module or update HANDOFF_PATH)",
            )
            return
        if module.tree is None:
            yield self.finding(
                module,
                ast.Module(body=[], type_ignores=[]),
                f"{HANDOFF_PATH} unparseable — handoff decision totality "
                "unverifiable (rule fails closed)",
            )
            return
        constants = _module_string_constants(module.tree)
        roles = resolved_tuple(module.tree, ROLES_NAME, constants)
        causes = resolved_tuple(module.tree, CAUSES_NAME, constants)
        for name, space in ((ROLES_NAME, roles), (CAUSES_NAME, causes)):
            if space is None:
                yield self.finding(
                    module,
                    module.tree,
                    f"{name} tuple of resolvable string constants not "
                    f"found in {module.rel_path} — handoff decision "
                    "totality unverifiable (rule fails closed)",
                )
        if roles is None or causes is None:
            return
        for table_name, nested in HANDOFF_TABLES:
            value = _module_assignment(module.tree, table_name)
            if not isinstance(value, ast.Dict):
                yield self.finding(
                    module,
                    module.tree,
                    f"decision table {table_name} missing from "
                    f"{module.rel_path} (or not a dict literal) — handoff "
                    "decision totality unverifiable (rule fails closed)",
                )
                continue
            if nested:
                yield from self._check_nested(module, table_name, value, roles, causes, constants)
            else:
                yield from self._check_flat(module, table_name, value, causes, constants)

    def _resolve_keys(
        self, keys, constants: Dict[str, str]
    ) -> Optional[Set[str]]:
        out: Set[str] = set()
        for key in keys:
            resolved = _resolve_key(key, constants) if key is not None else None
            if resolved is None:
                return None
            out.add(resolved)
        return out

    def _check_flat(
        self,
        module: Module,
        table_name: str,
        value: ast.Dict,
        causes: Set[str],
        constants: Dict[str, str],
    ) -> Iterator[Finding]:
        keys = self._resolve_keys(value.keys, constants)
        if keys is None:
            yield self.finding(
                module,
                value,
                f"decision table {table_name} has a key that is neither a "
                "string literal nor a resolvable constant — totality "
                "unverifiable (rule fails closed)",
            )
            return
        for missing in sorted(causes - keys):
            yield self.finding(
                module,
                value,
                f"{table_name} missing handoff fault cause '{missing}' — "
                "every transfer fault must classify to a taxonomy action",
            )
        for extra in sorted(keys - causes):
            yield self.finding(
                module,
                value,
                f"{table_name} declares unknown handoff fault cause "
                f"'{extra}' — not a member of {CAUSES_NAME}",
            )

    def _check_nested(
        self,
        module: Module,
        table_name: str,
        value: ast.Dict,
        roles: Set[str],
        causes: Set[str],
        constants: Dict[str, str],
    ) -> Iterator[Finding]:
        outer = self._resolve_keys(value.keys, constants)
        if outer is None:
            yield self.finding(
                module,
                value,
                f"decision table {table_name} has a role key that is "
                "neither a string literal nor a resolvable constant — "
                "totality unverifiable (rule fails closed)",
            )
            return
        for missing in sorted(roles - outer):
            yield self.finding(
                module,
                value,
                f"{table_name} missing replica role '{missing}' — every "
                "role must declare its per-cause handoff decisions",
            )
        for extra in sorted(outer - roles):
            yield self.finding(
                module,
                value,
                f"{table_name} declares unknown replica role '{extra}' — "
                f"not a member of {ROLES_NAME}",
            )
        for key_node, inner_value in zip(value.keys, value.values):
            role = _resolve_key(key_node, constants) if key_node is not None else None
            if role is None or role not in roles:
                continue  # already reported above
            if not isinstance(inner_value, ast.Dict):
                yield self.finding(
                    module,
                    inner_value,
                    f"{table_name}['{role}'] is not a dict literal — "
                    "per-cause totality unverifiable (rule fails closed)",
                )
                continue
            inner = self._resolve_keys(inner_value.keys, constants)
            if inner is None:
                yield self.finding(
                    module,
                    inner_value,
                    f"{table_name}['{role}'] has a cause key that is "
                    "neither a string literal nor a resolvable constant — "
                    "totality unverifiable (rule fails closed)",
                )
                continue
            for missing in sorted(causes - inner):
                yield self.finding(
                    module,
                    inner_value,
                    f"{table_name}['{role}'] missing handoff fault cause "
                    f"'{missing}' — every role x cause pair must declare "
                    "its re-placement decision",
                )
            for extra in sorted(inner - causes):
                yield self.finding(
                    module,
                    inner_value,
                    f"{table_name}['{role}'] declares unknown handoff "
                    f"fault cause '{extra}' — not a member of {CAUSES_NAME}",
                )
