"""NX018: NEXUS_* env / config / docs parity (ISSUE 16).

The launcher contract is the env surface: every ``NEXUS_*`` variable the
tree reads is an operator-facing knob, and the only place an operator can
discover it is ``docs/ENVIRONMENT.md``.  This rule keeps the three views
welded together, two-way:

* every ``NEXUS_*`` read in the scanned tree must have a row in the
  docs env table (undocumented knob -> finding at the READ site);
* every row in the docs env table must still have at least one read
  (stale row -> finding against the docs table, so a renamed knob cannot
  leave its documentation behind);
* each row's "Parsed at" module list must name only modules that really
  read the variable (a moved parse site must move its row).

Reads are detected structurally, not by grep: ``environ[K]`` /
``environ.get(K)`` / ``environ.pop(K)`` / ``os.getenv(K)`` / ``K in
environ`` where the mapping's terminal name is an environ alias and ``K``
is a string literal or a module-level ``ENV_FOO = "NEXUS_..."`` constant.
A ``NEXUS_``-prefixed key the rule cannot resolve to a literal fails
CLOSED (the parity set would silently lose a knob).  ``NEXUS__*`` (double
underscore) is the generic config-overlay namespace handled by
``core/config.py`` and is exempt — its keys are field-derived, not a
fixed catalog.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, register

ENV_DOC_PATH = "docs/ENVIRONMENT.md"

#: terminal names an environ mapping travels under in this tree:
#: ``os.environ``, a bare ``environ`` import, and the ``from_env(e)`` /
#: ``def parse(env)`` parameter idioms of the config parsers
_ENV_BASES = frozenset({"environ", "env", "e", "_e", "_env"})

_VAR_RE = re.compile(r"^NEXUS_[A-Z0-9][A-Z0-9_]*$")
_OVERLAY_PREFIX = "NEXUS__"

#: docs table row: | `NEXUS_X` | type | `a.py`, `b.py` | description |
_ROW_RE = re.compile(r"^\|\s*`(NEXUS_[A-Z0-9_]+)`\s*\|([^|]*)\|([^|]*)\|(.*)\|\s*$")


def _terminal(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``ENV_FOO = "NEXUS_..."`` string constants."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _key_exprs(tree: ast.Module) -> Iterator[Tuple[ast.AST, ast.expr]]:
    """(report node, key expression) for every structural env read."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in ("get", "pop")
                and _terminal(func.value) in _ENV_BASES
                and node.args
            ):
                yield node, node.args[0]
            elif _terminal(func) == "getenv" and node.args:
                yield node, node.args[0]
        elif (
            isinstance(node, ast.Subscript)
            and _terminal(node.value) in _ENV_BASES
            and isinstance(node.ctx, ast.Load)
        ):
            yield node, node.slice
        elif (
            isinstance(node, ast.Compare)
            and len(node.ops) == 1
            and isinstance(node.ops[0], (ast.In, ast.NotIn))
            and _terminal(node.comparators[0]) in _ENV_BASES
        ):
            yield node, node.left


def env_reads(module: Module) -> Tuple[List[Tuple[ast.AST, str]], List[ast.AST]]:
    """(resolved NEXUS_* reads, unresolvable NEXUS-suspect key sites)."""
    reads: List[Tuple[ast.AST, str]] = []
    unresolved: List[ast.AST] = []
    if module.tree is None:
        return reads, unresolved
    constants = _module_constants(module.tree)
    for node, key in _key_exprs(module.tree):
        value: Optional[str] = None
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            value = key.value
        elif isinstance(key, ast.Name):
            value = constants.get(key.id)
            if value is None:
                # not a module-level string constant: a loop variable or
                # parameter — only suspect when the name itself says env
                if key.id.upper().startswith("ENV_"):
                    unresolved.append(node)
                continue
        else:
            # f-string / concatenation building a key: suspect when any
            # literal fragment carries the NEXUS_ prefix
            fragments = [
                c.value
                for c in ast.walk(key)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            ]
            if any(f.startswith("NEXUS_") for f in fragments):
                unresolved.append(node)
            continue
        if value.startswith(_OVERLAY_PREFIX):
            continue
        if _VAR_RE.match(value):
            reads.append((node, value))
    return reads, unresolved


def parse_doc_rows(text: str) -> List[Tuple[int, str, str, List[str], str]]:
    """(line, var, type, parsed-at rel-paths, description) per table row."""
    rows = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        match = _ROW_RE.match(line.strip())
        if not match:
            continue
        var, type_col, parsed_at, desc = match.groups()
        paths = [p.strip().strip("`") for p in parsed_at.split(",") if p.strip()]
        rows.append((lineno, var, type_col.strip(), paths, desc.strip()))
    return rows


@register
class EnvDocsParityRule(Rule):
    """NX018: every NEXUS_* env read documented in docs/ENVIRONMENT.md,
    every documented row still read, parse-site column accurate."""

    rule_id = "NX018"
    description = "NEXUS_* env reads and docs/ENVIRONMENT.md must agree two-way"

    def check_project(self, project: Project) -> Iterator[Finding]:
        #: var -> [(module, node)], in scan order
        read_sites: Dict[str, List[Tuple[Module, ast.AST]]] = {}
        any_read = False
        for module in project.modules:
            reads, unresolved = env_reads(module)
            for node, var in reads:
                any_read = True
                read_sites.setdefault(var, []).append((module, node))
            for node in unresolved:
                any_read = True
                yield self.finding(
                    module,
                    node,
                    "env read with a key NX018 cannot resolve to a NEXUS_* "
                    "literal — the env/docs parity set would silently lose "
                    "this knob; use a string literal or a module-level "
                    "ENV_* constant (fails closed)",
                )
        if not any_read:
            return  # tree without an env surface has nothing to document

        doc_file = os.path.join(project.root, ENV_DOC_PATH)
        anchor = next((m for m in project.modules if m.tree is not None), None)
        if anchor is None:
            return
        try:
            with open(doc_file, "r", encoding="utf-8") as fh:
                doc_text = fh.read()
        except OSError:
            yield self.finding(
                anchor,
                anchor.tree,
                f"{ENV_DOC_PATH} is missing but the tree reads "
                f"{len(read_sites)} NEXUS_* variable(s) — the env surface "
                "must be documented (fails closed)",
            )
            return

        rows = parse_doc_rows(doc_text)
        documented: Dict[str, Tuple[int, str, List[str]]] = {}
        for lineno, var, type_col, paths, _desc in rows:
            documented[var] = (lineno, type_col, paths)

        for var in sorted(read_sites):
            module, node = read_sites[var][0]
            if var not in documented:
                yield self.finding(
                    module,
                    node,
                    f"{var} is read here but has no row in {ENV_DOC_PATH} — "
                    "add it to the env table (Variable | Type | Parsed at | "
                    "Description)",
                )
                continue
            lineno, type_col, doc_paths = documented[var]
            if not type_col:
                yield self.finding(
                    module,
                    node,
                    f"{var}'s row in {ENV_DOC_PATH}:{lineno} has an empty "
                    "Type column",
                )
            actual = {m.rel_path for m, _n in read_sites[var]}
            for path in doc_paths:
                if not any(a == path or a.endswith("/" + path) for a in actual):
                    yield self.finding(
                        module,
                        node,
                        f"{var}'s row in {ENV_DOC_PATH}:{lineno} says it is "
                        f"parsed at {path}, but no scanned read site lives "
                        f"there (actual: {', '.join(sorted(actual))}) — the "
                        "parse site moved without its docs row",
                    )

        for var, (lineno, _type_col, doc_paths) in sorted(documented.items()):
            if var in read_sites:
                continue
            # scope gate: a partial scan (tpu_nexus/ alone, tools/ alone,
            # --changed fast path) must not call the OTHER tree's rows
            # stale — a row is only judged when at least one of its
            # declared parse-site modules is in this lint invocation
            if not any(project.find_module(p) is not None for p in doc_paths):
                continue
            yield self.finding(
                anchor,
                anchor.tree,
                f"{ENV_DOC_PATH}:{lineno} documents {var} but nothing in "
                "the scanned tree reads it — stale row (renamed or "
                "removed knob)",
            )
