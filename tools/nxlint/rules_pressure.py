"""Pressure-plane rules (ISSUE 15): the SLO/load-snapshot contracts.

NX016  pressure-taxonomy totality + snapshot/metric parity:

       (a) every grading table in ``tpu_nexus/serving/loadstats.py``
       (:data:`PRESSURE_TABLES`) must be TOTAL over ``PRESSURE_STATES`` —
       the NX001 decision-taxonomy pattern: adding a pressure state
       without declaring its severity rank and supervisor consequence is
       a static-analysis error, not a midnight KeyError in the fleet
       controller's reconcile;

       (b) every NUMERIC field of ``LoadSnapshot`` / ``FleetSnapshot``
       must have a matching ``core/telemetry.METRIC_NAMES`` row under the
       ``load.`` / ``fleet.load.`` prefix — and every registry row under
       those prefixes must still be a snapshot field (two-way, the NX015
       shape).  Together with NX015 (registry row ⇔ literal emission)
       this makes the three surfaces — dataclass, registry, gauges —
       mutually un-driftable.

       Fails closed when the module, the states tuple, a table, a
       snapshot class, or the registry is missing/unparseable.

NX021  router decision totality (ISSUE 19; the issue numbered it NX020,
       which PR 14's flow-integrity rule already holds — renumbered):
       the fleet router's decision tables in
       ``tpu_nexus/serving/router.py`` (:data:`ROUTER_TABLES` —
       ``ROUTE_ELIGIBILITY`` mapping a replica's pressure grade to its
       admission eligibility, ``SCALE_DECISIONS`` mapping the fleet
       grade to a capacity verdict) must be TOTAL over the SAME
       ``PRESSURE_STATES`` NX016 governs: adding a pressure state
       without declaring how the router treats it and whether it scales
       the fleet is a static-analysis error, not a midnight KeyError on
       the admission path.  Keys resolve against BOTH modules' string
       constants (the tables may spell states literally or via the
       loadstats constants).  Fails closed when the router module or a
       table is missing/unresolvable; a broken loadstats side is NX016's
       finding, not a second one here.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, register
from tools.nxlint.rules_telemetry import (
    REGISTRY_NAME,
    TELEMETRY_PATH,
    registered_metrics,
)

LOADSTATS_PATH = "tpu_nexus/serving/loadstats.py"
STATES_NAME = "PRESSURE_STATES"

#: the tables that must be total over PRESSURE_STATES.  A new table keyed
#: by pressure grades should be added here (the repo-clean gate's review
#: is the backstop, as with NX015's receiver set).
PRESSURE_TABLES = ("PRESSURE_SEVERITY", "PRESSURE_ACTIONS")

ROUTER_PATH = "tpu_nexus/serving/router.py"

#: the router decision tables that must be total over PRESSURE_STATES
#: (NX021).  Same backstop as PRESSURE_TABLES: a new grade-keyed table
#: in the router belongs in this tuple.
ROUTER_TABLES = ("ROUTE_ELIGIBILITY", "SCALE_DECISIONS")

#: snapshot class -> metric-name prefix its numeric fields mirror into
SNAPSHOT_PREFIXES = (
    ("LoadSnapshot", "load."),
    ("FleetSnapshot", "fleet.load."),
)

_NUMERIC_ANNOTATIONS = frozenset({"int", "float"})


def _module_string_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments — how the pressure
    states are spelled (the NX001 constant-class convention, flattened)."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _module_assignment(tree: ast.Module, name: str) -> Optional[ast.expr]:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in stmt.targets
        ):
            return stmt.value
        if (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
            and stmt.target.id == name
        ):
            return stmt.value
    return None


def _resolve_key(node: ast.expr, constants: Dict[str, str]) -> Optional[str]:
    """A states-tuple element or table key -> the state string it names:
    a literal string, or a Name referring to a module string constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return constants.get(node.id)
    return None


def pressure_states(tree: ast.Module) -> Optional[Set[str]]:
    """The declared pressure state space, or None when the tuple is
    missing or any element fails to resolve (the rule fails closed)."""
    constants = _module_string_constants(tree)
    value = _module_assignment(tree, STATES_NAME)
    if not isinstance(value, (ast.Tuple, ast.List)):
        return None
    states: Set[str] = set()
    for element in value.elts:
        resolved = _resolve_key(element, constants)
        if resolved is None:
            return None
        states.add(resolved)
    return states or None


def table_keys(
    tree: ast.Module, name: str
) -> Optional[Tuple[Set[str], ast.expr]]:
    """The resolved key set of one grading table (and its node for
    findings); None when missing, not a dict literal, or a key fails to
    resolve."""
    constants = _module_string_constants(tree)
    value = _module_assignment(tree, name)
    if not isinstance(value, ast.Dict):
        return None
    keys: Set[str] = set()
    for key in value.keys:
        resolved = _resolve_key(key, constants) if key is not None else None
        if resolved is None:
            return None
        keys.add(resolved)
    return keys, value


def numeric_snapshot_fields(
    tree: ast.Module, class_name: str
) -> Optional[Dict[str, ast.AST]]:
    """field name -> declaring node for every ``int``/``float``-annotated
    field of one snapshot dataclass; None when the class is missing."""
    cls = next(
        (
            n
            for n in tree.body
            if isinstance(n, ast.ClassDef) and n.name == class_name
        ),
        None,
    )
    if cls is None:
        return None
    fields: Dict[str, ast.AST] = {}
    for stmt in cls.body:
        if (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
            and isinstance(stmt.annotation, ast.Name)
            and stmt.annotation.id in _NUMERIC_ANNOTATIONS
        ):
            fields[stmt.target.id] = stmt
    return fields


@register
class PressureContractRule(Rule):
    """NX016 (module doc): taxonomy totality over PRESSURE_STATES plus
    two-way snapshot-field / metric-registry parity."""

    rule_id = "NX016"
    description = (
        "pressure tables total over PRESSURE_STATES; LoadSnapshot/"
        "FleetSnapshot numeric fields <-> METRIC_NAMES load./fleet.load. "
        "rows (two-way)"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        module = project.find_module(LOADSTATS_PATH)
        if module is None:
            return  # project doesn't contain the serving tree (tools subtree)
        if module.tree is None:
            yield self.finding(
                module,
                ast.Module(body=[], type_ignores=[]),
                f"{LOADSTATS_PATH} unparseable — pressure contracts "
                "unverifiable (rule fails closed)",
            )
            return
        yield from self._check_totality(module)
        yield from self._check_parity(project, module)

    # -- (a) taxonomy totality -------------------------------------------------

    def _check_totality(self, module: Module) -> Iterator[Finding]:
        states = pressure_states(module.tree)
        if states is None:
            yield self.finding(
                module,
                module.tree,
                f"{STATES_NAME} tuple of resolvable state constants not "
                f"found in {module.rel_path} — pressure totality "
                "unverifiable (rule fails closed; fix pressure_states or "
                "restore the tuple)",
            )
            return
        for table_name in PRESSURE_TABLES:
            resolved = table_keys(module.tree, table_name)
            if resolved is None:
                yield self.finding(
                    module,
                    module.tree,
                    f"grading table {table_name} missing from "
                    f"{module.rel_path} (or not a dict literal with "
                    "resolvable keys) — totality unverifiable (rule fails "
                    "closed)",
                )
                continue
            keys, node = resolved
            for missing in sorted(states - keys):
                yield self.finding(
                    module,
                    node,
                    f"{table_name} missing pressure state '{missing}' — "
                    "every state must declare its "
                    f"{'severity rank' if table_name == 'PRESSURE_SEVERITY' else 'supervisor consequence'}",
                )
            for extra in sorted(keys - states):
                yield self.finding(
                    module,
                    node,
                    f"{table_name} declares unknown pressure state "
                    f"'{extra}' — not a member of {STATES_NAME}",
                )

    # -- (b) snapshot/metric parity --------------------------------------------

    def _check_parity(
        self, project: Project, module: Module
    ) -> Iterator[Finding]:
        registry_module = project.find_module(TELEMETRY_PATH)
        if registry_module is None or registry_module.tree is None:
            return  # NX015 already owns the missing-registry finding
        registry = registered_metrics(registry_module.tree)
        if registry is None:
            return  # ditto — one finding per broken registry is enough
        # longest prefix first, so a fleet.load.* row never misclassifies
        # under a shorter overlapping prefix
        prefixes: List[Tuple[str, str]] = sorted(
            SNAPSHOT_PREFIXES, key=lambda pair: -len(pair[1])
        )
        claimed: Set[str] = set()
        for class_name, prefix in prefixes:
            fields = numeric_snapshot_fields(module.tree, class_name)
            if fields is None:
                yield self.finding(
                    module,
                    module.tree,
                    f"snapshot class {class_name} not found in "
                    f"{module.rel_path} — snapshot/metric parity "
                    "unverifiable (rule fails closed)",
                )
                continue
            for name, node in sorted(fields.items()):
                row = prefix + name
                claimed.add(row)
                if row not in registry:
                    yield self.finding(
                        module,
                        node,
                        f"{class_name}.{name} has no '{row}' row in "
                        f"{REGISTRY_NAME} ({TELEMETRY_PATH}) — every "
                        "numeric snapshot field must be chartable (add "
                        "the row + its literal gauge, and regenerate the "
                        "docs table)",
                    )
            for row in sorted(registry):
                if not row.startswith(prefix) or row in claimed:
                    continue
                claimed.add(row)
                yield self.finding(
                    registry_module,
                    registry[row],
                    f"{REGISTRY_NAME} documents '{row}' but {class_name} "
                    f"has no numeric field '{row[len(prefix):]}' — remove "
                    "the row or restore the field",
                )


@register
class RouterContractRule(Rule):
    """NX021 (module doc): the fleet router's decision tables must be
    total over the pressure taxonomy."""

    rule_id = "NX021"
    description = (
        "fleet router decision tables (ROUTE_ELIGIBILITY/SCALE_DECISIONS) "
        "total over PRESSURE_STATES"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        loadstats = project.find_module(LOADSTATS_PATH)
        if loadstats is None:
            return  # project doesn't contain the serving tree (tools subtree)
        if loadstats.tree is None or pressure_states(loadstats.tree) is None:
            return  # NX016 owns the broken-loadstats finding
        states = pressure_states(loadstats.tree)
        module = project.find_module(ROUTER_PATH)
        if module is None:
            yield self.finding(
                loadstats,
                loadstats.tree,
                f"{ROUTER_PATH} missing — the fleet's routing/scale "
                "decision tables are unverifiable (rule fails closed; "
                "restore the module or update ROUTER_PATH)",
            )
            return
        if module.tree is None:
            yield self.finding(
                module,
                ast.Module(body=[], type_ignores=[]),
                f"{ROUTER_PATH} unparseable — routing/scale decision "
                "totality unverifiable (rule fails closed)",
            )
            return
        # the tables may spell states literally or via either module's
        # constants (router imports the PRESSURE_* names from loadstats)
        constants = {
            **_module_string_constants(loadstats.tree),
            **_module_string_constants(module.tree),
        }
        assert states is not None
        for table_name in ROUTER_TABLES:
            value = _module_assignment(module.tree, table_name)
            if not isinstance(value, ast.Dict):
                yield self.finding(
                    module,
                    module.tree,
                    f"decision table {table_name} missing from "
                    f"{module.rel_path} (or not a dict literal) — "
                    "routing/scale totality unverifiable (rule fails "
                    "closed)",
                )
                continue
            keys: Set[str] = set()
            unresolved = False
            for key in value.keys:
                resolved = _resolve_key(key, constants) if key is not None else None
                if resolved is None:
                    unresolved = True
                    break
                keys.add(resolved)
            if unresolved:
                yield self.finding(
                    module,
                    value,
                    f"decision table {table_name} has a key that is neither "
                    "a string literal nor a resolvable state constant — "
                    "totality unverifiable (rule fails closed)",
                )
                continue
            for missing in sorted(states - keys):
                yield self.finding(
                    module,
                    value,
                    f"{table_name} missing pressure state '{missing}' — "
                    "every state must declare "
                    f"{'its admission eligibility' if table_name == 'ROUTE_ELIGIBILITY' else 'whether it scales the fleet'}",
                )
            for extra in sorted(keys - states):
                yield self.finding(
                    module,
                    value,
                    f"{table_name} declares unknown pressure state "
                    f"'{extra}' — not a member of {STATES_NAME}",
                )
