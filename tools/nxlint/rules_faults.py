"""Chaos-coverage rule: the fault registry must stay drilled.

NX009  every fault mode registered in ``workload/faults.py`` must be
       exercised by at least one test under ``tests/``.  The PR 4/5 "no
       vacuous drills" guarantee is runtime-only: a loop that configures a
       fault raises if the fault never fires — but nothing stops a NEW
       fault mode from landing with no drill at all, in which case the
       guarantee never even arms.  This rule makes it static: a mode
       string (frozenset member of a ``*_FAULT_MODES`` table, or a
       ``plan.mode == "..."``-style comparison) with no quoted occurrence
       in any test file fails the repo gate.

       The check is deliberately a literal-string approximation — a test
       that names the mode but never runs it would pass.  The runtime
       vacuous-drill guards cover that half; this rule covers the
       "nobody ever wrote the drill" half, and fails CLOSED (no modes
       found, or no tests directory ⇒ finding).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, Optional

from tools.nxlint.engine import Finding, Module, Project, Rule, register

FAULTS_PATH = "workload/faults.py"
TESTS_DIR = "tests"

#: string-comparison left-hand sides that denote the fault mode: the plan's
#: attribute (``plan.mode``/``self.mode``) or a bare ``mode`` local
_MODE_NAMES = frozenset({"mode"})


def registered_fault_modes(tree: ast.Module) -> Dict[str, ast.AST]:
    """Fault-mode string -> the AST node declaring it.

    Two declaration shapes, matching how faults.py registers modes:

    * members of a module-level ``frozenset({...})``/set/tuple/list assigned
      to a name ending in ``_FAULT_MODES``;
    * ``== "literal"`` comparisons whose left side is ``*.mode`` or
      ``mode`` (the ``maybe_inject`` dispatch chain and wrapper guards).
    """
    modes: Dict[str, ast.AST] = {}
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            targets = [stmt.target]
        if not any(t.id.endswith("_FAULT_MODES") for t in targets):
            continue
        value = stmt.value
        if isinstance(value, ast.Call):  # frozenset({...}) / frozenset([...])
            value = value.args[0] if value.args else None
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    modes.setdefault(elt.value, elt)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not isinstance(node.ops[0], ast.Eq):
            continue
        left = node.left
        is_mode = (isinstance(left, ast.Attribute) and left.attr in _MODE_NAMES) or (
            isinstance(left, ast.Name) and left.id in _MODE_NAMES
        )
        if not is_mode:
            continue
        comp = node.comparators[0]
        if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
            modes.setdefault(comp.value, comp)
    return modes


def _test_corpus(root: str) -> Optional[str]:
    """Concatenated source of every python file under ``<root>/tests``;
    None when the directory is absent or holds no python files."""
    tests_dir = os.path.join(root, TESTS_DIR)
    if not os.path.isdir(tests_dir):
        return None
    chunks = []
    for dirpath, dirnames, filenames in os.walk(tests_dir):
        dirnames[:] = sorted(
            d for d in dirnames if not d.startswith(".") and d != "__pycache__"
        )
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, name), "r", encoding="utf-8") as fh:
                    chunks.append(fh.read())
            except (OSError, UnicodeDecodeError):
                continue  # unreadable test files are NX000's business
    if not chunks:
        return None
    return "\n".join(chunks)


@register
class ChaosCoverageRule(Rule):
    """NX009: a registered fault mode nobody drills is a recovery path
    nobody has proven — the exact gap the vacuous-drill runtime guards
    cannot see (they only fire once a drill EXISTS)."""

    rule_id = "NX009"
    description = "every registered fault mode must be exercised by at least one test"

    def check_project(self, project: Project) -> Iterator[Finding]:
        module = project.find_module(FAULTS_PATH)
        if module is None or module.tree is None:
            return  # project doesn't contain the fault registry (tools tree)
        modes = registered_fault_modes(module.tree)
        if not modes:
            yield self.finding(
                module,
                module.tree,
                "no fault modes found in workload/faults.py — the mode "
                "extraction no longer matches the registry shape (rule "
                "fails closed; fix registered_fault_modes)",
            )
            return
        corpus = _test_corpus(project.root)
        if corpus is None:
            yield self.finding(
                module,
                module.tree,
                f"no test files found under {os.path.join(project.root, TESTS_DIR)} "
                "— chaos coverage unverifiable (rule fails closed)",
            )
            return
        for mode in sorted(modes):
            if f'"{mode}"' in corpus or f"'{mode}'" in corpus:
                continue
            yield self.finding(
                module,
                modes[mode],
                f"fault mode '{mode}' is registered but no test under "
                f"{TESTS_DIR}/ names it — add a chaos test exercising the "
                "mode (the runtime vacuous-drill guard can only protect "
                "drills that exist)",
            )
