"""nxlint core: findings, suppressions, baselines, the rule registry and
the project/module model rules run against.

Design mirrors the shape of a go/analysis pass: a ``Rule`` sees either one
parsed module at a time (``check_module``) or the whole scanned project
(``check_project``) for cross-file invariants, and yields ``Finding``s.
The driver handles everything else — per-line ``# nxlint: disable=RULE``
suppressions, baseline files (adopt-a-legacy-tree workflow), output
formatting and the exit-code contract (0 clean / 1 findings / 2 usage
error, same contract as tools/check_coverage.py).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from collections import Counter
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, Iterator, List, Mapping, Optional, Sequence, Type

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

#: per-line suppression:  ``x = 1  # nxlint: disable=NX001`` (comma-separated
#: rule ids, or ``all``), optionally followed by a rationale.  The id list
#: ends at the first non-id word so ``disable=NX010 static by construction``
#: still suppresses NX010.
_SUPPRESS_RE = re.compile(
    r"#\s*nxlint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


@dataclass(frozen=True)
class Finding:
    """One diagnostic: where, which rule, and a message precise enough that
    (file, rule, message) identifies the problem across line renumbering —
    that triple is the baseline fingerprint."""

    file: str
    line: int
    col: int
    rule_id: str
    severity: str
    message: str

    def fingerprint(self) -> str:
        raw = f"{self.file}::{self.rule_id}::{self.message}"
        return hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule_id} [{self.severity}] {self.message}"

    def to_json(self) -> Dict[str, object]:
        out = asdict(self)
        out["fingerprint"] = self.fingerprint()
        return out


class Module:
    """One parsed python file."""

    def __init__(self, path: str, rel_path: str, source: str) -> None:
        self.path = path
        #: repo-relative posix path — what findings and baselines carry
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        self._stmt_openings: Optional[Dict[int, int]] = None
        try:
            self.tree = ast.parse(source, filename=rel_path)
        except SyntaxError as exc:
            self.parse_error = exc

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed_rules(self, lineno: int) -> frozenset:
        m = _SUPPRESS_RE.search(self.line_text(lineno))
        if not m:
            return frozenset()
        return frozenset(part.strip() for part in m.group(1).split(",") if part.strip())

    def _statement_opening_lines(self) -> Dict[int, int]:
        """Continuation line -> opening line of its statement, so a
        ``# nxlint: disable`` on the first line of a formatter-wrapped call
        suppresses findings anchored to ANY line of that statement.  Simple
        statements map their whole span; compound statements map only their
        HEADER (a wrapped ``if``/``with`` condition) — a disable on a
        ``def``/``if`` line must never blanket the nested body."""
        if self._stmt_openings is not None:
            return self._stmt_openings
        spans: Dict[int, int] = {}
        compound = (
            ast.FunctionDef,
            ast.AsyncFunctionDef,
            ast.ClassDef,
            ast.If,
            ast.For,
            ast.AsyncFor,
            ast.While,
            ast.With,
            ast.AsyncWith,
            ast.Try,
        )
        if self.tree is not None:
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt):
                    continue
                end = getattr(node, "end_lineno", None)
                if end is None or end <= node.lineno:
                    continue
                if isinstance(node, compound):
                    if isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                    ):
                        continue
                    children = [
                        stmt.lineno
                        for field in ("body", "orelse", "finalbody")
                        for stmt in getattr(node, field, []) or []
                    ] + [h.lineno for h in getattr(node, "handlers", []) or []]
                    if children:
                        end = min(end, min(children) - 1)
                    if end <= node.lineno:
                        continue
                for line in range(node.lineno + 1, end + 1):
                    spans.setdefault(line, node.lineno)
        self._stmt_openings = spans
        return spans

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressed_rules(finding.line)
        opening = self._statement_opening_lines().get(finding.line)
        if opening is not None:
            rules = rules | self.suppressed_rules(opening)
        return finding.rule_id in rules or "all" in rules


class Project:
    """All modules of one lint run plus the root they were collected under
    (cross-file rules locate their targets by path suffix)."""

    def __init__(self, root: str, modules: Sequence[Module]) -> None:
        self.root = root
        self.modules = list(modules)
        self._by_rel = {m.rel_path: m for m in self.modules}

    def find_module(self, path_suffix: str) -> Optional[Module]:
        suffix = path_suffix.replace(os.sep, "/")
        exact = self._by_rel.get(suffix)
        if exact is not None:
            return exact
        for module in self.modules:
            if module.rel_path.endswith("/" + suffix):
                return module
        return None

    def read_sibling(self, module: Module, filename: str) -> Optional[str]:
        """Non-python artifact (schema.cql) next to a scanned module."""
        candidate = os.path.join(os.path.dirname(module.path), filename)
        if not os.path.isfile(candidate):
            return None
        with open(candidate, "r", encoding="utf-8") as fh:
            return fh.read()


class Rule:
    """Base class: subclass, set the class attributes, override one of the
    two hooks, and ``@register`` it."""

    rule_id: str = "NX000"
    severity: str = SEVERITY_ERROR
    description: str = ""

    def check_module(self, module: Module) -> Iterator[Finding]:
        return iter(())

    def check_project(self, project: Project) -> Iterator[Finding]:
        return iter(())

    def finding(self, module: Module, node: ast.AST, message: str) -> Finding:
        return Finding(
            file=module.rel_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            rule_id=self.rule_id,
            severity=self.severity,
            message=message,
        )


class RuleVisitor(ast.NodeVisitor):
    """Visitor base for module rules: carries the module and collects
    findings via ``report``."""

    def __init__(self, rule: Rule, module: Module) -> None:
        self.rule = rule
        self.module = module
        self.findings: List[Finding] = []

    def report(self, node: ast.AST, message: str) -> None:
        self.findings.append(self.rule.finding(self.module, node, message))


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls: Type[Rule]) -> Type[Rule]:
    rule = rule_cls()
    if rule.rule_id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.rule_id}")
    _REGISTRY[rule.rule_id] = rule
    return rule_cls


def all_rules() -> List[Rule]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


# -- driver --------------------------------------------------------------------


def collect_modules(paths: Sequence[str], root: str) -> List[Module]:
    files: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            # fail loud: a typo'd path must not make a gate pass vacuously
            # with zero files scanned
            raise FileNotFoundError(f"nxlint: no such path: {path}")
        if os.path.isfile(path):
            files.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames if not d.startswith(".") and d != "__pycache__"
            )
            files.extend(
                os.path.join(dirpath, f) for f in sorted(filenames) if f.endswith(".py")
            )
    modules = []
    seen = set()
    for path in files:
        abs_path = os.path.abspath(path)
        if abs_path in seen:  # overlapping path args must not double-lint
            continue
        seen.add(abs_path)
        rel = os.path.relpath(abs_path, os.path.abspath(root))
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as exc:
            # keep the 0/1/2 exit contract: surface as an NX000 finding
            # instead of a traceback
            module = Module(path, rel, "")
            module.parse_error = SyntaxError(f"unreadable file: {exc}")
            modules.append(module)
            continue
        modules.append(Module(path, rel, source))
    return modules


def lint_project(
    project: Project,
    rules: Optional[Iterable[Rule]] = None,
    baseline: Optional[Mapping] = None,
) -> List[Finding]:
    """Run rules over the project; suppressed and baselined findings are
    dropped here so callers only ever see actionable ones."""
    rules = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    for module in project.modules:
        if module.parse_error is not None:
            findings.append(
                Finding(
                    file=module.rel_path,
                    line=module.parse_error.lineno or 1,
                    col=module.parse_error.offset or 0,
                    rule_id="NX000",
                    severity=SEVERITY_ERROR,
                    message=f"syntax error: {module.parse_error.msg}",
                )
            )
            continue
        for rule in rules:
            for finding in rule.check_module(module):
                if not module.is_suppressed(finding):
                    findings.append(finding)
    for rule in rules:
        for finding in rule.check_project(project):
            module = project.find_module(finding.file)
            if module is not None and module.is_suppressed(finding):
                continue
            findings.append(finding)
    findings = sorted(findings, key=lambda f: (f.file, f.line, f.col, f.rule_id))
    if baseline:
        # occurrence-counted: baselining ONE `except Exception` in a file
        # must not grandfather a second identical one added later (the
        # fingerprint is (file, rule, message), which repeats)
        allowance = Counter(
            dict(baseline) if isinstance(baseline, Mapping) else list(baseline)
        )
        kept = []
        for finding in findings:
            fp = finding.fingerprint()
            if allowance.get(fp, 0) > 0:
                allowance[fp] -= 1
            else:
                kept.append(finding)
        findings = kept
    return findings


def lint_paths(
    paths: Sequence[str],
    root: str = ".",
    rules: Optional[Iterable[Rule]] = None,
    baseline: Optional[Mapping] = None,
) -> List[Finding]:
    project = Project(root, collect_modules(paths, root))
    return lint_project(project, rules=rules, baseline=baseline)


# -- baseline files ------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, int]:
    """Fingerprint -> how many occurrences the baseline grandfathers."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return Counter(entry["fingerprint"] for entry in data.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    data = {"findings": [f.to_json() for f in findings]}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
