"""NX017: lock discipline on thread-reachable mutations (ISSUE 16).

The serving and workload planes are single-threaded BY CONTRACT almost
everywhere — the dispatch loop owns ``ServingEngine``/``KVBlockManager``
state, the fleet reconciler owns replica state — and the few real threads
(the step watchdog, the emergency saver, the telemetry shipper) touch
shared state through explicit locks.  That contract is invisible to the
runtime until a race corrupts a KV page table; this rule makes it
checkable:

1.  Thread ENTRY POINTS are every callable handed to
    ``threading.Thread(target=...)`` / ``threading.Timer(...)``, resolved
    through the call graph (``self._run`` bound methods, nested closures,
    imported functions).
2.  The REACHABLE set is the call-graph closure from those entries.
3.  Inside reachable methods of a GUARDED class (table below), any
    mutation of ``self`` state must lexically sit under ``with
    self.<lock>:`` for lock-owning classes — or is a finding outright for
    classes whose contract is "never touched from a thread" (lock
    ``None``: the single-threaded seam).

Fails closed: a guarded class that disappears from its module, or a
declared lock attribute that is never assigned in the class, is itself a
finding — a rename must update the table, not silently disarm the rule.
An unresolvable thread target inside the flow-scoped strict modules is
also a finding (the closure cannot be trusted if its roots are unknown).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.nxlint.engine import Finding, Module, Project, Rule, register
from tools.nxlint.flow import (
    CallGraph,
    FunctionInfo,
    flow_for,
    frame_nodes,
    is_strict_module,
)

#: class name -> (defining module rel_path, owning lock attribute).
#: Lock ``None`` declares the SINGLE-THREADED SEAM contract: the class is
#: owned by one loop (dispatch loop, reconciler) and must never be mutated
#: from code reachable off a thread entry point.  The ISSUE names a
#: ``DispatchPipeline``; this tree's equivalent staged-dispatch actor is
#: ``PipelineStageActor`` (``core/pipeline.py``), whose cross-thread
#: ingest handoff is guarded by ``_ingest_lock``.
GUARDED_CLASSES: Dict[str, Tuple[str, Optional[str]]] = {
    "ServingEngine": ("tpu_nexus/serving/engine.py", None),
    "KVBlockManager": ("tpu_nexus/serving/cache_manager.py", None),
    "ServingFleet": ("tpu_nexus/serving/fleet.py", None),
    "FleetSupervisor": ("tpu_nexus/serving/fleet.py", None),
    "StepWatchdog": ("tpu_nexus/workload/health.py", "_lock"),
    "PipelineStageActor": ("tpu_nexus/core/pipeline.py", "_ingest_lock"),
}

#: method names whose call on a ``self`` attribute mutates it in place.
#: ``set`` (threading.Event) and queue ``put*`` are deliberately absent:
#: events and queues ARE synchronization primitives.
_MUTATOR_METHODS = frozenset(
    {
        "append",
        "appendleft",
        "extend",
        "insert",
        "remove",
        "pop",
        "popleft",
        "popitem",
        "clear",
        "update",
        "add",
        "discard",
        "setdefault",
    }
)

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})


def _terminal(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _self_attr(expr: ast.expr) -> Optional[str]:
    """``self.x`` -> "x"; also the base attr of ``self.x[k]``."""
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def thread_entry_exprs(tree: ast.Module) -> Iterator[Tuple[ast.Call, ast.expr]]:
    """The callable expressions handed to Thread/Timer constructors."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal(node.func)
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    yield node, kw.value
        elif name == "Timer":
            if len(node.args) >= 2:
                yield node, node.args[1]
            for kw in node.keywords:
                if kw.arg == "function":
                    yield node, kw.value


class _Mutation:
    """One ``self``-state mutation site inside a method's own frame."""

    def __init__(self, node: ast.AST, attr: str, desc: str) -> None:
        self.node = node
        self.attr = attr
        self.desc = desc


def _frame_mutations(fn: ast.AST, skip_attrs: Set[str]) -> List[_Mutation]:
    out: List[_Mutation] = []
    for node in frame_nodes(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if isinstance(node, ast.AnnAssign) and node.value is None:
                continue
            for target in targets:
                elts = target.elts if isinstance(target, (ast.Tuple, ast.List)) else [target]
                for elt in elts:
                    attr = _self_attr(elt)
                    if attr is not None and attr not in skip_attrs:
                        out.append(_Mutation(node, attr, f"assignment to self.{attr}"))
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                attr = _self_attr(target)
                if attr is not None and attr not in skip_attrs:
                    out.append(_Mutation(node, attr, f"del of self.{attr}"))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATOR_METHODS
        ):
            attr = _self_attr(node.func.value)
            if attr is not None and attr not in skip_attrs:
                out.append(
                    _Mutation(node, attr, f"self.{attr}.{node.func.attr}() mutation")
                )
    return out


def _under_lock(node: ast.AST, fn: ast.AST, parents: Dict[ast.AST, ast.AST], lock: str) -> bool:
    """True when ``node`` sits lexically inside ``with self.<lock>:`` within
    ``fn``'s frame."""
    cur = parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                if _self_attr(item.context_expr) == lock:
                    return True
        cur = parents.get(cur)
    return False


@register
class LockDisciplineRule(Rule):
    """NX017: guarded-class state reachable from thread entry points must
    be mutated under the owning lock (or not at all, for classes whose
    contract is single-threaded ownership)."""

    rule_id = "NX017"
    description = (
        "thread-reachable mutations of guarded classes must hold the owning lock"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        try:
            graph = flow_for(project)
        except Exception:  # noqa: BLE001 - without a graph there is no reachability; NX020 already reports the breakage
            return
        guarded = self._active_guarded(project)
        yield from self._fails_closed(project, guarded)
        if not guarded:
            return
        entries, unresolved = self._entries(graph)
        for module, call in unresolved:
            if is_strict_module(module.rel_path):
                yield self.finding(
                    module,
                    call,
                    "thread target does not resolve through the call graph — "
                    "the lock-discipline closure cannot see past it; bind a "
                    "named function or justify a disable",
                )
        reachable = self._closure(graph, entries)
        for info, entry_desc in reachable:
            cls = info.class_name
            if cls not in guarded:
                continue
            decl_path, lock = GUARDED_CLASSES[cls]
            if decl_path not in info.module.rel_path and info.module.rel_path != decl_path:
                continue
            idx = graph.index_for(info.module)
            skip = {lock} if lock else set()
            for mut in _frame_mutations(info.node, skip):
                if lock is None:
                    yield self.finding(
                        info.module,
                        mut.node,
                        f"{mut.desc} in {cls}.{info.name} is reachable from a "
                        f"thread entry point ({entry_desc}) but {cls} is a "
                        "single-threaded seam — route the mutation through the "
                        "owning loop, or give the class a lock and register it "
                        "in the NX017 table",
                    )
                elif not _under_lock(mut.node, info.node, idx.parents, lock):
                    yield self.finding(
                        info.module,
                        mut.node,
                        f"{mut.desc} in {cls}.{info.name} is reachable from a "
                        f"thread entry point ({entry_desc}) and must hold "
                        f"self.{lock} (wrap it in 'with self.{lock}:')",
                    )

    # -- pieces ---------------------------------------------------------------

    def _active_guarded(self, project: Project) -> Set[str]:
        """Guarded classes whose declared module is in this lint scope."""
        active: Set[str] = set()
        for cls, (rel_path, _lock) in GUARDED_CLASSES.items():
            if project.find_module(rel_path) is not None:
                active.add(cls)
        return active

    def _fails_closed(self, project: Project, active: Set[str]) -> Iterator[Finding]:
        for cls, (rel_path, lock) in GUARDED_CLASSES.items():
            module = project.find_module(rel_path)
            if module is None or module.tree is None:
                continue  # module outside this lint invocation's paths
            cls_node = next(
                (
                    n
                    for n in module.tree.body
                    if isinstance(n, ast.ClassDef) and n.name == cls
                ),
                None,
            )
            if cls_node is None:
                yield self.finding(
                    module,
                    module.tree,
                    f"guarded class {cls} no longer exists in {rel_path} — "
                    "NX017's table is stale; update tools/nxlint/"
                    "rules_concurrency.py (fails closed)",
                )
                continue
            if lock is None:
                continue
            if not self._lock_assigned(cls_node, lock):
                yield self.finding(
                    module,
                    cls_node,
                    f"guarded class {cls} declares lock self.{lock} in NX017's "
                    "table but never assigns it a threading lock — the "
                    "discipline check has nothing to hold (fails closed)",
                )

    @staticmethod
    def _lock_assigned(cls_node: ast.ClassDef, lock: str) -> bool:
        for node in ast.walk(cls_node):
            if (
                isinstance(node, ast.Assign)
                and any(_self_attr(t) == lock for t in node.targets)
                and isinstance(node.value, ast.Call)
                and _terminal(node.value.func) in _LOCK_FACTORIES
            ):
                return True
        return False

    def _entries(
        self, graph: CallGraph
    ) -> Tuple[List[Tuple[FunctionInfo, str]], List[Tuple[Module, ast.Call]]]:
        entries: List[Tuple[FunctionInfo, str]] = []
        unresolved: List[Tuple[Module, ast.Call]] = []
        for idx in graph.indexes.values():
            for call, expr in thread_entry_exprs(idx.module.tree):
                infos = self._resolve_target(graph, idx, call, expr)
                desc = (
                    f"thread target at {idx.module.rel_path}:{call.lineno}"
                )
                if infos:
                    entries.extend((info, desc) for info in infos)
                else:
                    unresolved.append((idx.module, call))
        return entries, unresolved

    @staticmethod
    def _resolve_target(graph, idx, call: ast.Call, expr: ast.expr) -> List[FunctionInfo]:
        if isinstance(expr, ast.Name):
            return [info for info, _via in graph._resolve_name(expr.id, call, idx)]
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            cls = idx.enclosing_class(call)
            if cls is not None:
                return graph._lookup_method(idx, cls, expr.attr)
        if isinstance(expr, ast.Lambda):
            return []  # opaque: surfaces as unresolved in strict modules
        return []

    @staticmethod
    def _closure(
        graph: CallGraph, entries: List[Tuple[FunctionInfo, str]]
    ) -> List[Tuple[FunctionInfo, str]]:
        reachable: Dict[int, Tuple[FunctionInfo, str]] = {}
        work = list(entries)
        while work:
            info, desc = work.pop()
            if id(info.node) in reachable:
                continue
            reachable[id(info.node)] = (info, desc)
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    for callee, _via in graph.resolve_call(node, info.module):
                        if id(callee.node) not in reachable:
                            work.append((callee, desc))
        return list(reachable.values())
