#!/usr/bin/env python3
"""nxtrace — convert flight-recorder dumps to Chrome trace-event format.

The serving engine's flight recorder (``tpu_nexus/serving/tracing.py``)
serializes its per-step ring + the implicated requests' span timelines to
JSON at the incident seams (step-fault escalation, DeviceStateLost,
drain/SIGTERM, fleet replica-lost).  This tool turns one of those dumps
into the Chrome trace-event format that ``chrome://tracing`` and
https://ui.perfetto.dev load directly:

    python -m tools.nxtrace /tmp/tpu-nexus-traces/nxtrace-123-001-drain.json
    # -> nxtrace-123-001-drain.trace.json (open it in perfetto)

Rendering (docs/OBSERVABILITY.md has the schemas):

* each implicated request is a named thread under the "requests" process:
  derived **slices** for its queued (submit→admitted) and prefill
  (prefill_dispatch→prefill_complete) phases plus a whole-life slice, and
  an **instant** per raw span event with its attrs as args — in overlap
  mode the distinct decode_dispatch/materialize instants make the
  one-step-late deferral visible on the timeline;
* the flight-recorder ring renders under the "engine" process: **counter**
  tracks for queue depth / slots / block pool / deferred lanes, and a
  per-step **slice** on the dispatch track sized to that step's host
  dispatch seconds.

Dependency-free stdlib, same exit contract as the other tools: 0 ok,
2 usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

#: trace-event process ids (arbitrary but stable — perfetto groups by pid)
PID_REQUESTS = 1
PID_ENGINE = 2

#: span-phase pairs rendered as duration slices on a request's track
_PHASE_SLICES = (
    ("queued", "submit", "admitted"),
    ("prefill", "prefill_dispatch", "prefill_complete"),
)

#: flight-recorder fields rendered as engine counter tracks
_COUNTERS = (
    "queue_depth",
    "slots_used",
    "deferred_slots",
    "blocks_free",
    "blocks_used",
    "blocks_reclaimable",
)


def _us(t: float) -> float:
    """Monotonic seconds -> trace-event microseconds."""
    return t * 1e6


def _thread_meta(pid: int, tid: int, name: str) -> Dict[str, Any]:
    return {
        "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
        "args": {"name": name},
    }


def _request_events(timeline: Dict[str, Any], tid: int) -> List[Dict[str, Any]]:
    rid = timeline.get("request_id", "?")
    events = timeline.get("events", [])
    out: List[Dict[str, Any]] = [_thread_meta(PID_REQUESTS, tid, rid)]
    by_name: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        by_name.setdefault(ev["name"], ev)  # first occurrence wins
        out.append(
            {
                "ph": "i",  # instant, thread-scoped
                "pid": PID_REQUESTS,
                "tid": tid,
                "name": ev["name"],
                "ts": _us(ev["t"]),
                "s": "t",
                "args": ev.get("attrs") or {},
            }
        )
    for slice_name, start_ev, end_ev in _PHASE_SLICES:
        a, b = by_name.get(start_ev), by_name.get(end_ev)
        if a is not None and b is not None and b["t"] >= a["t"]:
            out.append(
                {
                    "ph": "X",
                    "pid": PID_REQUESTS,
                    "tid": tid,
                    "name": slice_name,
                    "ts": _us(a["t"]),
                    "dur": max(1.0, _us(b["t"] - a["t"])),
                    "args": {},
                }
            )
    if events:
        terminal = events[-1]
        args = dict(terminal.get("attrs") or {})
        out.append(
            {
                "ph": "X",
                "pid": PID_REQUESTS,
                "tid": tid,
                "name": f"request {rid} [{args.get('state', '?')}]",
                "ts": _us(events[0]["t"]),
                "dur": max(1.0, _us(terminal["t"] - events[0]["t"])),
                "args": args,
            }
        )
    return out


def _engine_events(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = [
        _thread_meta(PID_ENGINE, 0, "dispatch"),
    ]
    for rec in records:
        t = rec.get("t")
        if t is None:
            continue
        for field in _COUNTERS:
            if field in rec:
                out.append(
                    {
                        "ph": "C",
                        "pid": PID_ENGINE,
                        "tid": 0,
                        "name": field,
                        "ts": _us(t),
                        "args": {field: rec[field]},
                    }
                )
        dispatch_s = float(rec.get("dispatch_s", 0.0))
        # the step record's timestamp is taken AFTER its dispatches, so
        # the slice ends at t and extends dispatch_s back — approximate,
        # but the relative widths (the host tax per step) are exact
        out.append(
            {
                "ph": "X",
                "pid": PID_ENGINE,
                "tid": 0,
                "name": f"step {rec.get('step', '?')}",
                "ts": _us(t - dispatch_s),
                "dur": max(1.0, _us(dispatch_s)),
                "args": {
                    k: v
                    for k, v in rec.items()
                    if k not in ("t", "batch") and not isinstance(v, dict)
                },
            }
        )
        if rec.get("faults"):
            out.append(
                {
                    "ph": "i",
                    "pid": PID_ENGINE,
                    "tid": 0,
                    "name": f"fault: {','.join(rec['faults'])}",
                    "ts": _us(t),
                    "s": "p",  # process-scoped: draws across the track
                    "args": {"faults": rec["faults"]},
                }
            )
    return out


def convert(dump: Dict[str, Any]) -> Dict[str, Any]:
    """One flight-recorder dump dict -> a Chrome trace-event dict
    (``{"traceEvents": [...], ...}``).  Raises ValueError on a payload
    that is not a flight-recorder dump."""
    schema = dump.get("schema", "")
    if not str(schema).startswith("tpu-nexus-flight-recorder"):
        raise ValueError(
            f"not a flight-recorder dump (schema={schema!r}); expected "
            "an artifact written by serving/tracing.FlightRecorder.dump"
        )
    events: List[Dict[str, Any]] = []
    for tid, timeline in enumerate(dump.get("implicated", []), start=1):
        tl = timeline.get("timeline")
        if tl:
            events.extend(_request_events(tl, tid))
        else:
            events.append(
                _thread_meta(
                    PID_REQUESTS, tid,
                    f"{timeline.get('request_id', '?')} (no timeline)",
                )
            )
    events.extend(_engine_events(dump.get("records", [])))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "reason": dump.get("reason", ""),
            "wall_time": dump.get("wall_time"),
            "implicated_total": dump.get("implicated_total"),
            "source": "tpu-nexus nxtrace",
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.nxtrace",
        description="convert flight-recorder dumps to Chrome trace-event JSON",
    )
    parser.add_argument("dump", help="flight-recorder JSON artifact")
    parser.add_argument(
        "-o", "--out",
        help="output path (default: <dump>.trace.json alongside the input)",
    )
    args = parser.parse_args(argv)
    try:
        with open(args.dump, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
        trace = convert(payload)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"nxtrace: {exc}", file=sys.stderr)
        return 2
    out = args.out or (
        args.dump[: -len(".json")] + ".trace.json"
        if args.dump.endswith(".json")
        else args.dump + ".trace.json"
    )
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    print(
        f"nxtrace: {len(trace['traceEvents'])} trace events -> {out} "
        "(load in chrome://tracing or ui.perfetto.dev)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
