"""Int8/int4 WEIGHT quality gate (ISSUE 17; mirrors tools/int8_gate_1b.py).

The int8-KV gate (INT8_GATE_1B_r05.json) priced the KV-cache half of
quantized serving; this script gates the WEIGHT half at both widths:
train the same noisy affine-bigram corpus the r5 gate used, then measure
held-out perplexity

  * through ``make_eval_step`` (teacher-forced forward): bf16 vs int8
    weight-only vs packed int4 with group-wise scales — the numbers the
    fused-dequant serving path (ops/quant_matmul.py) needs;
  * through the DECODE path (prefill + decode_step scan, the code serving
    actually runs): the same three trees, bf16 KV throughout so the
    delta is pure weight error.

Gate bars match INT8_GATE_1B_r05.json: int8 rel ppl delta < 1%; int4
< 2% (4-bit group-wise is the "+ int8 KV" error-budget tier of the r5
gate, and the r5 combined bar was 2%).

``NEXUS_GATE_MODEL`` picks the config: ``nexus_1b`` (default, chip
scale), ``nexus_moe``, ``small``, or ``tiny`` — CPU-feasible tiers for
boxes without an accelerator (PR 2 precedent: report the honest floor);
the artifact records which ran.  The int4 artifact tier is ``small``
(hidden 256): group-wise int4 noise on a contraction of width K scales
like 1/sqrt(K), and tiny's hidden 64 is too narrow to meet a bar
calibrated at 1B scale no matter the group size (measured sweep at
hidden 64: group 64 → +5.2% ppl, 16 → +3.3%, 8 → +2.3%; hidden 256
passes — see PERF.md r13).  ``NEXUS_QUANT_GROUP`` overrides the int4
group size (0 = DEFAULT_INT4_GROUP).

Prints one JSON line per measurement:

    python tools/int4_gate_1b.py                       # chip, ~10 min
    NEXUS_GATE_MODEL=tiny python tools/int4_gate_1b.py # CPU tier
"""

from __future__ import annotations

import functools
import json
import os
import sys
import tempfile
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from tpu_nexus.models import LlamaConfig
    from tpu_nexus.models.generate import teacher_forced_decode_ce
    from tpu_nexus.models.quant import quantize_params
    from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
    from tpu_nexus.workload.data import token_file_batches, write_token_npy
    from tpu_nexus.workload.train import (
        TrainConfig,
        init_train_state,
        make_eval_step,
        make_train_step,
    )

    steps = int(os.environ.get("NEXUS_GATE_STEPS", "300"))
    model = os.environ.get("NEXUS_GATE_MODEL", "nexus_1b")
    group = int(os.environ.get("NEXUS_QUANT_GROUP", "0") or 0)
    batch, seq = 16, 2048
    vocab = 32768

    # same corpus recipe as the r5 gate (512-token support of the vocab:
    # learnable structure in minutes, which is all the quantization delta
    # needs to be meaningful) — scaled down with the model on the CPU tier
    support = 512
    n = 8 * 1024 * 1024
    if model == "nexus_moe":
        from tpu_nexus.models import MoeConfig

        cfg = MoeConfig.nexus_moe()
        batch = 32
    elif model == "small":
        # CPU artifact tier: hidden 256 is the narrowest width at which
        # the 1B-calibrated int4 bar is meetable (noise ~ 1/sqrt(K));
        # seq 128 keeps the host train under ~20 min
        cfg = LlamaConfig(
            vocab_size=1024, hidden=256, n_layers=2, n_heads=8, n_kv_heads=4,
            head_dim=32, intermediate=512, max_seq_len=256, remat=False,
        )
        batch, seq = 8, 128
        n = 1024 * 1024
    elif model == "tiny":
        # CPU smoke tier: structure-identical shapes, vocab wide enough to
        # hold the 512-token support; corpus/batch sized for minutes on a
        # host.  Too narrow for the int4 bar (see module docstring) — use
        # ``small`` for the artifact run
        cfg = LlamaConfig.tiny(vocab_size=1024)
        batch, seq = 8, 256
        n = 1024 * 1024
    else:
        cfg = LlamaConfig.nexus_1b()
    rng = np.random.default_rng(0)
    toks = np.empty(n, np.int32)
    toks[0] = 1
    noise = rng.integers(0, 16, size=n)
    for i in range(1, n):
        toks[i] = (toks[i - 1] * 31 + 7 + noise[i]) % support
    path = write_token_npy(
        os.path.join(tempfile.gettempdir(), f"gate4_corpus_{model}.npy"), toks
    )

    tcfg = TrainConfig(warmup_steps=20, total_steps=max(steps, 2), learning_rate=1e-3)
    mesh = build_mesh(MeshSpec(fsdp=-1))
    state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
    step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
    split = int(n * 0.98)
    train_data = token_file_batches(path, batch=batch, seq_len=seq, seed=1, end=split)

    t0 = time.perf_counter()
    with mesh:
        for i in range(steps):
            state, m = step_fn(state, jnp.asarray(next(train_data)))
            if (i + 1) % 50 == 0:
                print(json.dumps({
                    "phase": "train", "step": i + 1, "loss": round(float(m["loss"]), 4),
                    "elapsed_s": round(time.perf_counter() - t0, 1),
                }), flush=True)

    eval_fn = make_eval_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
    heldout = token_file_batches(path, batch=batch, seq_len=seq, seed=99, start=split)
    eval_batches = [jnp.asarray(next(heldout)) for _ in range(8)]

    def forward_ppl(params):
        with mesh:
            ces = [float(eval_fn({"params": params}, b)["ce_loss"]) for b in eval_batches]
        return float(np.exp(np.mean(ces)))

    params = state["params"]
    qparams8 = quantize_params(params, mode="int8")
    qparams4 = quantize_params(params, mode="int4", group=group)
    ppl_full = forward_ppl(params)
    ppl_int8 = forward_ppl(qparams8)
    ppl_int4 = forward_ppl(qparams4)
    assert ppl_full < support / 2, (
        f"model did not train (ppl {ppl_full} vs {support}-support uniform {support})"
    )
    print(json.dumps({
        "phase": "gate_forward", "model": model, "steps": steps, "support": support,
        "int4_group": group, "ppl_bf16": round(ppl_full, 4),
        "ppl_int8w": round(ppl_int8, 4), "ppl_int4w": round(ppl_int4, 4),
        "rel_delta_int8": round((ppl_int8 - ppl_full) / ppl_full, 6),
        "rel_delta_int4": round((ppl_int4 - ppl_full) / ppl_full, 6),
        "gate_int8_lt": 0.01, "gate_int4_lt": 0.02,
        "pass": bool(abs(ppl_int8 - ppl_full) / ppl_full < 0.01
                     and abs(ppl_int4 - ppl_full) / ppl_full < 0.02),
    }), flush=True)

    # -- decode-path gate (the exact serving code; bf16 KV so the delta is
    # pure weight error) ----------------------------------------------------
    dec_seq = min(1024, cfg.max_seq_len)
    dec_batch = 8

    @functools.partial(jax.jit, static_argnames=())
    def decode_ce(p, batch_toks):
        return teacher_forced_decode_ce(p, batch_toks, cfg)

    dec_stream = token_file_batches(path, batch=dec_batch, seq_len=dec_seq, seed=7, start=split)
    dec_batches = [jnp.asarray(next(dec_stream)) for _ in range(2)]

    def decode_ppl(p):
        return float(np.exp(np.mean([float(decode_ce(p, b)) for b in dec_batches])))

    d_full = decode_ppl(params)
    d_int8 = decode_ppl(qparams8)
    d_int4 = decode_ppl(qparams4)
    print(json.dumps({
        "phase": "gate_decode", "model": model, "seq": dec_seq,
        "int4_group": group, "ppl_bf16": round(d_full, 4),
        "ppl_int8w": round(d_int8, 4), "ppl_int4w": round(d_int4, 4),
        "rel_delta_int8": round((d_int8 - d_full) / d_full, 6),
        "rel_delta_int4": round((d_int4 - d_full) / d_full, 6),
        "gate_int8_lt": 0.01, "gate_int4_lt": 0.02,
        "pass": bool(abs(d_int8 - d_full) / d_full < 0.01
                     and abs(d_int4 - d_full) / d_full < 0.02),
    }), flush=True)


if __name__ == "__main__":
    main()
