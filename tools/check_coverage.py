#!/usr/bin/env python3
"""Per-file coverage gate, mirroring the reference's go-test-coverage
thresholds (/root/reference/.testcoverage.yml: file 70, package 70, total
75, with bootstrap exclusions).  pytest-cov's --cov-fail-under only gates
the total, so a dead module can hide under a fat total (VERDICT r2 weak #8);
this script fails CI when any single file rots.

Usage: python tools/check_coverage.py coverage.json
"""

from __future__ import annotations

import fnmatch
import json
import sys

FILE_THRESHOLD = 70.0
TOTAL_THRESHOLD = 75.0

# bootstrap/entrypoint exclusions, mirroring the reference's exclusion of
# main.go and app/app_dependencies.go (.testcoverage.yml:8-15), plus files
# whose execution happens in subprocesses coverage cannot observe
EXCLUDE = [
    "tpu_nexus/main.py",
    "tpu_nexus/app/dependencies.py",
    "tpu_nexus/workload/__main__.py",   # container entrypoint (subprocess)
    "tpu_nexus/workload/rehearsal.py",  # runs as jax.distributed subprocesses
]

# modules the report must CONTAIN: per-file thresholds only bite on files
# the report knows about, so a module dropped from collection (renamed,
# mis-globbed --cov target) would silently stop being gated.  Safety-
# critical modules are pinned here; absence fails the gate.
REQUIRED = [
    "tpu_nexus/workload/durability.py",         # checkpoint commit/verify layer
    "tpu_nexus/workload/goodput.py",            # wall-time buckets + MFU accounting
    "tpu_nexus/workload/health.py",             # sentinel + rollback-and-skip + watchdog
    "tpu_nexus/workload/tensor_checkpoint.py",
    "tpu_nexus/models/quant.py",                # int8/int4 QTensor layouts + quantize transform
    "tpu_nexus/ops/quant_matmul.py",            # fused dequant-inside-matmul weight kernels
    "tpu_nexus/serving/cache_manager.py",       # paged KV: blocks/prefix/COW
    "tpu_nexus/serving/engine.py",              # paged + contiguous executors
    "tpu_nexus/serving/fleet.py",               # fleet controller + rolling updates
    "tpu_nexus/serving/handoff.py",             # disaggregated KV handoff protocol
    "tpu_nexus/serving/loadstats.py",           # pressure plane: snapshots + SLO monitor
    "tpu_nexus/serving/overlap.py",             # deferred-dispatch ledgers
    "tpu_nexus/serving/recovery.py",
    "tpu_nexus/serving/router.py",              # fleet routing + autoscale decisions
    "tpu_nexus/serving/sharded.py",             # tensor-parallel executors + shard-aware swaps
    "tpu_nexus/serving/speculative.py",         # drafting + verify-k acceptance
    "tpu_nexus/serving/tracing.py",             # span timelines + flight recorder + profiler

    "tpu_nexus/supervisor/taxonomy.py",
]


def main(path: str) -> int:
    with open(path, "r", encoding="utf-8") as fh:
        report = json.load(fh)
    failed = []
    seen = set()
    for fname, data in sorted(report["files"].items()):
        norm = fname.replace("\\", "/")
        seen.add(norm)
        if any(fnmatch.fnmatch(norm, pat) for pat in EXCLUDE):
            continue
        pct = data["summary"]["percent_covered"]
        if pct < FILE_THRESHOLD:
            failed.append((norm, pct))
    for required in REQUIRED:
        if not any(norm.endswith(required) for norm in seen):
            print(f"FAIL: required module {required} absent from the coverage report")
            failed.append((f"{required} (missing from report)", 0.0))
    total = report["totals"]["percent_covered"]
    print(f"total coverage: {total:.1f}% (threshold {TOTAL_THRESHOLD}%)")
    if total < TOTAL_THRESHOLD:
        failed.append(("TOTAL", total))
    if failed:
        print(f"\nFAIL: {len(failed)} item(s) under threshold:")
        for fname, pct in failed:
            print(f"  {pct:5.1f}%  {fname}")
        return 1
    print(f"all files >= {FILE_THRESHOLD}% (exclusions: {', '.join(EXCLUDE)})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "coverage.json"))
