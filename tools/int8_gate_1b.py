"""Deployment-scale int8 quality gate (VERDICT r4 weak #4 / next #5).

The tiny-model perplexity gate (tests/test_quant.py) showed +0.002%; this
script runs the SAME gate at nexus_1b scale on the real chip: train ~200
corpus steps (minutes at ~18k tok/s), then measure held-out perplexity

  * through ``make_eval_step`` (teacher-forced forward): bf16 vs int8
    weight-only — the number the 1.47x serving speedup needs;
  * through the DECODE path (prefill + decode_step scan, the code serving
    actually runs): bf16 cache vs int8 KV cache vs int8 weights + int8 KV.

Prints one JSON line per measurement; run on the chip:

    python tools/int8_gate_1b.py          # ~10 min end to end
    NEXUS_GATE_STEPS=500 python tools/int8_gate_1b.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import tempfile
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from tpu_nexus.models import LlamaConfig
    from tpu_nexus.models.generate import teacher_forced_decode_ce
    from tpu_nexus.models.quant import quantize_params
    from tpu_nexus.parallel import LOGICAL_RULES_FSDP_TP, MeshSpec, build_mesh
    from tpu_nexus.workload.data import token_file_batches, write_token_npy
    from tpu_nexus.workload.train import (
        TrainConfig,
        init_train_state,
        make_eval_step,
        make_train_step,
    )

    steps = int(os.environ.get("NEXUS_GATE_STEPS", "300"))
    model = os.environ.get("NEXUS_GATE_MODEL", "nexus_1b")
    batch, seq = 16, 2048
    vocab = 32768

    # noisy affine bigram corpus over a 512-token SUPPORT of the 32k vocab:
    # a full-vocab chain is a 32768-entry random map a 1B model cannot
    # memorize in 200 steps (measured: loss stuck at the ln(32768)=10.40
    # uniform floor; a 4096-support chain still sat at its ln(4096) support
    # floor at step 200), while the 512-support chain gives the weights
    # real, quickly-learnable structure — which is all the quantization
    # delta needs to be meaningful
    rng = np.random.default_rng(0)
    n = 8 * 1024 * 1024
    support = 512
    toks = np.empty(n, np.int32)
    toks[0] = 1
    noise = rng.integers(0, 16, size=n)
    for i in range(1, n):
        toks[i] = (toks[i - 1] * 31 + 7 + noise[i]) % support
    path = write_token_npy(os.path.join(tempfile.gettempdir(), "gate1b_corpus.npy"), toks)

    if model == "nexus_moe":
        from tpu_nexus.models import MoeConfig

        cfg = MoeConfig.nexus_moe()
        batch = 32  # the MoE preset trains ~3x faster per token; keep minutes
    else:
        cfg = LlamaConfig.nexus_1b()
    tcfg = TrainConfig(warmup_steps=20, total_steps=max(steps, 2), learning_rate=1e-3)
    mesh = build_mesh(MeshSpec(fsdp=-1))
    state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
    step_fn = make_train_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
    split = int(n * 0.98)
    train_data = token_file_batches(path, batch=batch, seq_len=seq, seed=1, end=split)

    t0 = time.perf_counter()
    with mesh:
        for i in range(steps):
            state, m = step_fn(state, jnp.asarray(next(train_data)))
            if (i + 1) % 50 == 0:
                print(json.dumps({
                    "phase": "train", "step": i + 1, "loss": round(float(m["loss"]), 4),
                    "elapsed_s": round(time.perf_counter() - t0, 1),
                }), flush=True)

    eval_fn = make_eval_step(cfg, tcfg, mesh, LOGICAL_RULES_FSDP_TP)
    heldout = token_file_batches(path, batch=batch, seq_len=seq, seed=99, start=split)
    eval_batches = [jnp.asarray(next(heldout)) for _ in range(8)]

    def forward_ppl(params):
        with mesh:
            ces = [float(eval_fn({"params": params}, b)["ce_loss"]) for b in eval_batches]
        return float(np.exp(np.mean(ces)))

    params = state["params"]
    qparams = quantize_params(params)
    ppl_full = forward_ppl(params)
    ppl_int8 = forward_ppl(qparams)
    assert ppl_full < 256, f"model did not train (ppl {ppl_full} vs 512-support uniform 512)"
    print(json.dumps({
        "phase": "gate_forward", "model": model, "steps": steps,
        "ppl_bf16": round(ppl_full, 4), "ppl_int8w": round(ppl_int8, 4), "support": 512,
        "rel_delta": round((ppl_int8 - ppl_full) / ppl_full, 6),
        "gate_lt": 0.01, "pass": bool(abs(ppl_int8 - ppl_full) / ppl_full < 0.01),
    }), flush=True)

    # -- decode-path gate (the exact serving code; shared scorer) ----------
    dec_seq, dec_batch = 1024, 8

    @functools.partial(jax.jit, static_argnames=("kv_quant",))
    def decode_ce(p, batch_toks, kv_quant=""):
        return teacher_forced_decode_ce(p, batch_toks, cfg, kv_quant=kv_quant)

    dec_stream = token_file_batches(path, batch=dec_batch, seq_len=dec_seq, seed=7, start=split)
    dec_batches = [jnp.asarray(next(dec_stream)) for _ in range(2)]

    def decode_ppl(p, kv_quant=""):
        return float(np.exp(np.mean([
            float(decode_ce(p, b, kv_quant=kv_quant)) for b in dec_batches
        ])))

    d_full = decode_ppl(params)
    d_kv8 = decode_ppl(params, kv_quant="int8")
    d_both = decode_ppl(qparams, kv_quant="int8")
    print(json.dumps({
        "phase": "gate_decode", "model": model, "seq": dec_seq,
        "ppl_bf16": round(d_full, 4), "ppl_int8kv": round(d_kv8, 4),
        "ppl_int8w_int8kv": round(d_both, 4),
        "rel_delta_kv": round((d_kv8 - d_full) / d_full, 6),
        "rel_delta_both": round((d_both - d_full) / d_full, 6),
        "gate_kv_lt": 0.01, "gate_both_lt": 0.02,
        "pass": bool(abs(d_kv8 - d_full) / d_full < 0.01
                     and abs(d_both - d_full) / d_full < 0.02),
    }), flush=True)


if __name__ == "__main__":
    main()
